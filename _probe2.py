import time, numpy as np, jax, jax.numpy as jnp

@jax.jit
def tiny(x): return x + 1

small = jnp.zeros(2048*3, jnp.int32); small.block_until_ready()
tiny(small).block_until_ready()

# aged fetch: dispatch, async-copy, do 50ms of fake host work, then asarray
for wait in (0.0, 0.002, 0.01, 0.05):
    ts = []
    for _ in range(10):
        h = tiny(small); h.copy_to_host_async()
        t_w = time.perf_counter()
        while time.perf_counter() - t_w < wait:
            np.random.rand(10000).sum()
        t0 = time.perf_counter()
        np.asarray(h)
        ts.append(time.perf_counter() - t0)
    print(f"materialize after {wait*1000:4.0f}ms aging: avg {np.mean(ts)*1000:6.2f} ms  max {np.max(ts)*1000:6.2f}")

# k coalesced fetches materialized together after aging
for k in (1, 4, 16):
    hs = []
    for _ in range(k):
        h = tiny(small); h.copy_to_host_async(); hs.append(h)
    t_w = time.perf_counter()
    while time.perf_counter() - t_w < 0.05:
        np.random.rand(10000).sum()
    t0 = time.perf_counter()
    for h in hs: np.asarray(h)
    print(f"materialize {k:2d} aged handles together: {(time.perf_counter()-t0)*1000:6.2f} ms total")
