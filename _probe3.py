import time, threading, numpy as np, jax, jax.numpy as jnp

@jax.jit
def tiny(x): return x + 1
small = jnp.zeros(2048*3, jnp.int32); tiny(small).block_until_ready()

def bench_threads(nt, total=32):
    hs = [tiny(small) for _ in range(total)]
    for h in hs: h.copy_to_host_async()
    t0 = time.perf_counter()
    def work(chunk):
        for h in chunk: np.asarray(h)
    threads = [threading.Thread(target=work, args=(hs[i::nt],)) for i in range(nt)]
    for t in threads: t.start()
    for t in threads: t.join()
    dt = time.perf_counter()-t0
    print(f"{nt} threads, {total} fetches: {dt*1000:6.1f} ms total = {dt/total*1000:5.2f} ms/fetch")

for nt in (1, 2, 4, 8):
    bench_threads(nt)
