"""Direct unit tests for the slot-directory aggregator (ops/slot_agg.py):
spill tier, region lifecycle, collision detection, and differential checks
against the dict-based numpy oracle under random interleaved streams."""

import numpy as np
import pytest

from arroyo_tpu.ops.slot_agg import BinSlotDirectory, SlotAggregator

KW = dict(cap=64, batch_cap=64, emit_cap=64, region_size=16)


def _mk(backend="jax", kinds=("count", "sum"), dtypes=(np.int64, np.int64), **kw):
    args = {**KW, **kw}
    return SlotAggregator(kinds, dtypes, backend=backend, **args)


def _table(keys, bins, accs):
    return {
        (int(k), int(b)): tuple(float(a[i]) for a in accs)
        for i, (k, b) in enumerate(zip(keys.tolist(), bins.tolist()))
    }


# --------------------------------------------------------------- spill tier


def test_spill_tier_overflow_to_host_round_trip():
    """More distinct (bin, key) groups than device slots: the surplus lands
    in the host spill store and window closes still emit exact results."""
    agg = _mk()
    ora = _mk(backend="numpy")
    n_keys = 200  # 200 groups in one bin >> cap=64
    keys = np.arange(n_keys, dtype=np.uint64)
    ones = np.ones(n_keys, dtype=np.int64)
    vals = np.arange(n_keys, dtype=np.int64)
    for a in (agg, ora):
        a.update(keys, np.zeros(n_keys, dtype=np.int32), [ones, vals])
        a.update(keys, np.zeros(n_keys, dtype=np.int32), [ones, vals])
    assert len(agg.spill) == n_keys - KW["cap"]  # surplus spilled, no error
    k, b, accs = agg.extract(0, 1, 1)
    ok, ob, oaccs = ora.extract(0, 1, 1)
    assert _table(k, b, accs) == _table(ok, ob, oaccs)
    assert len(k) == n_keys
    # spill entries for the closed bin are gone
    assert not agg.spill


def test_snapshot_with_live_spill_entries():
    """snapshot() must include spill-tier entries (checkpoint correctness
    when the device table overflowed to host)."""
    agg = _mk()
    n_keys = 100
    keys = np.arange(n_keys, dtype=np.uint64)
    ones = np.ones(n_keys, dtype=np.int64)
    agg.update(keys, np.zeros(n_keys, dtype=np.int32), [ones, ones * 3])
    assert agg.spill  # overflowed
    sk, sb, saccs = agg.snapshot()
    assert len(sk) == n_keys
    got = _table(sk, sb, saccs)
    assert got == {(k, 0): (1.0, 3.0) for k in range(n_keys)}
    # snapshot is non-destructive: spill still live, extract still exact
    assert agg.spill
    k, b, accs = agg.extract(0, 1, 1)
    assert _table(k, b, accs) == got


def test_restore_merges_partial_counts():
    """Restore must scatter the snapshotted partial counts (merge mode), not
    +1 per restored row: a checkpoint taken after several updates of the
    same keys carries counts > 1, and a regression that routes restore
    through the constant-increment hot step would floor them back to 1."""
    agg = _mk()
    keys = np.arange(8, dtype=np.uint64)
    ones = np.ones(8, dtype=np.int64)
    vals = np.arange(8, dtype=np.int64)
    for _ in range(3):  # counts reach 3, sums reach 3*vals
        agg.update(keys, np.zeros(8, dtype=np.int32), [ones, vals])
    sk, sb, saccs = agg.snapshot()

    fresh = _mk()
    fresh.restore(sk, sb, saccs)
    k, b, accs = fresh.extract(0, 1, 1)
    assert _table(k, b, accs) == {
        (i, 0): (3.0, float(3 * i)) for i in range(8)
    }
    # and post-restore updates keep counting from the restored partials
    fresh2 = _mk()
    fresh2.restore(sk, sb, saccs)
    fresh2.update(keys, np.zeros(8, dtype=np.int32), [ones, vals])
    k2, b2, accs2 = fresh2.extract(0, 1, 1)
    assert _table(k2, b2, accs2) == {
        (i, 0): (4.0, float(4 * i)) for i in range(8)
    }


def test_spill_restore_round_trip():
    """snapshot -> restore into a fresh aggregator -> identical output
    (restore itself may spill again; that must be transparent)."""
    agg = _mk()
    n_keys = 150
    keys = np.arange(n_keys, dtype=np.uint64)
    ones = np.ones(n_keys, dtype=np.int64)
    vals = (np.arange(n_keys) * 7).astype(np.int64)
    agg.update(keys, np.zeros(n_keys, dtype=np.int32), [ones, vals])
    sk, sb, saccs = agg.snapshot()

    fresh = _mk()
    fresh.restore(sk, sb, saccs)
    k, b, accs = fresh.extract(0, 1, 1)
    assert _table(k, b, accs) == _table(sk, sb, saccs)


# ------------------------------------------------------------ region reuse


def test_region_exhaustion_and_reuse_after_close():
    d_regions = KW["cap"] // KW["region_size"]
    agg = _mk()
    d = agg.directory
    assert len(d.free_regions) == d_regions
    # fill the whole table with bin 0
    keys = np.arange(KW["cap"], dtype=np.uint64)
    ones = np.ones(KW["cap"], dtype=np.int64)
    agg.update(keys, np.zeros(KW["cap"], dtype=np.int32), [ones, ones])
    assert len(d.free_regions) == 0
    assert sorted(d.bin_regions) == [0]
    # new bin's groups must spill (no regions left)
    agg.update(keys[:8], np.ones(8, dtype=np.int32), [ones[:8], ones[:8]])
    assert len(agg.spill) == 8
    # close bin 0 -> all regions return to the free list
    k, b, accs = agg.extract(0, 1, 1)
    assert len(k) == KW["cap"]
    assert len(d.free_regions) == d_regions
    assert 0 not in d.bin_regions
    # bin 1 can now claim fresh regions; cleared slots hold identities
    agg.update(keys[:8], np.ones(8, dtype=np.int32), [ones[:8], ones[:8]])
    k2, b2, accs2 = agg.extract(1, 2, 2)
    got = _table(k2, b2, accs2)
    # spilled first update (1,1) merged with the post-close device update (1,1)
    assert got == {(k, 1): (2.0, 2.0) for k in range(8)}


def test_closed_boundary_blocks_stale_directory_hits():
    """After a close, a key from the closed bin re-appearing (late data path
    upstream allows this for new bins) must claim a fresh slot, not the stale
    directory entry."""
    agg = _mk()
    keys = np.arange(4, dtype=np.uint64)
    ones = np.ones(4, dtype=np.int64)
    agg.update(keys, np.zeros(4, dtype=np.int32), [ones, ones])
    agg.extract(0, 1, 1)  # closes bin 0, boundary=1
    assert agg.directory.boundary == 1
    agg.update(keys, np.full(4, 5, dtype=np.int32), [ones, ones * 9])
    k, b, accs = agg.extract(5, 6, 6)
    assert _table(k, b, accs) == {(k, 5): (1.0, 9.0) for k in range(4)}


# --------------------------------------------------------------- collision


def test_directory_code_collision_raises():
    d = BinSlotDirectory(cap=64, region_size=16)
    code = np.array([12345], dtype=np.uint64)
    d.lookup_or_assign(code, np.array([1], dtype=np.int64), np.array([0], dtype=np.int64))
    # same 64-bit code, different key identity -> must be detected
    with pytest.raises(RuntimeError, match="collision"):
        d.lookup_or_assign(code, np.array([2], dtype=np.int64), np.array([0], dtype=np.int64))


# ------------------------------------------------------------- differential


@pytest.mark.parametrize("kinds,dtypes", [
    (("count", "sum"), (np.int64, np.int64)),
    (("min", "max"), (np.int64, np.int64)),
    (("sum",), (np.float64,)),
])
def test_random_stream_differential_with_closes(kinds, dtypes):
    """Interleaved updates + incremental closes, small table forcing constant
    region churn and spill; jax path must match the numpy oracle exactly."""
    rng = np.random.default_rng(3)
    jx = _mk(kinds=kinds, dtypes=dtypes)
    ora = _mk(backend="numpy", kinds=kinds, dtypes=dtypes)
    got, want = {}, {}
    for step in range(24):
        n = 120
        keys = rng.integers(0, 90, n).astype(np.uint64)  # 90 keys/bin > cap=64
        bins = rng.integers(step // 4, step // 4 + 2, n).astype(np.int32)
        vals = rng.integers(1, 100, n).astype(np.int64)
        ins = [np.ones(n, dtype=np.int64) if k == "count" else vals for k in kinds]
        jx.update(keys, bins, ins)
        ora.update(keys, bins, ins)
        if step % 4 == 3:
            close = step // 4 + 1
            for agg, out in ((jx, got), (ora, want)):
                k, b, accs = agg.extract(0, close, close)
                t = _table(k, b, accs)
                assert not (set(t) & set(out)), "duplicate (key,bin) emitted"
                out.update(t)
    for agg, out in ((jx, got), (ora, want)):
        k, b, accs = agg.extract(0, 1 << 30, 1 << 30)
        out.update(_table(k, b, accs))
    assert got == want


def test_scan_range_nondestructive_with_spill():
    agg = _mk()
    n_keys = 100
    keys = np.arange(n_keys, dtype=np.uint64)
    ones = np.ones(n_keys, dtype=np.int64)
    agg.update(keys, np.zeros(n_keys, dtype=np.int32), [ones, ones])
    t1 = _table(*agg.scan_range(0, 1))
    t2 = _table(*agg.scan_range(0, 1))
    assert t1 == t2 and len(t1) == n_keys
    assert agg.spill  # scan must not consume spill entries


def test_native_dir_resolve_matches_numpy_fallback():
    """The C++ ah_dir_resolve fast path and the pure-numpy unique+probe path
    must produce identical aggregation results (same directory semantics,
    including claims after closes raising the boundary)."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(11)
    streams = [
        (rng.integers(0, 90, 120).astype(np.uint64),
         rng.integers(s // 4, s // 4 + 2, 120).astype(np.int32),
         rng.integers(1, 100, 120).astype(np.int64))
        for s in range(24)
    ]

    def run(disable_native):
        saved = native._lib, native._lib_failed
        saved_enabled = cfg.config().get("native.enabled", True)
        try:
            if disable_native:
                cfg.update({"native.enabled": False})
                native._lib = None
                native._lib_failed = True
            agg = _mk()
            out = {}
            for s, (keys, bins, vals) in enumerate(streams):
                agg.update(keys, bins, [np.ones(len(keys), dtype=np.int64), vals])
                if s % 4 == 3:
                    k, b, accs = agg.extract(0, s // 4 + 1, s // 4 + 1)
                    out.update(_table(k, b, accs))
            k, b, accs = agg.extract(0, 1 << 30, 1 << 30)
            out.update(_table(k, b, accs))
            return out
        finally:
            native._lib, native._lib_failed = saved
            cfg.update({"native.enabled": saved_enabled})

    assert run(False) == run(True)
