"""Microbench guard (slow): scaled-down q8/q5 through the full engine.

Asserts three things the coalescing work must keep true:
  - exact parity against the bench oracles (scaled event counts),
  - a VERY conservative events/s sanity floor (an order of magnitude under
    the measured numbers on the slowest box, so only a catastrophic
    regression — not scheduler noise — can trip it),
  - a ceiling on the number of emitted sink batches: accidental
    de-coalescing (per-window or per-key tiny emits sneaking back into the
    emission path) multiplies the batch count long before it shows up in
    wall-clock numbers.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _run(build, events, batch_size, queue_mult):
    import bench

    from arroyo_tpu import config as cfg
    from arroyo_tpu.engine import run_graph

    cfg.update({
        "pipeline.chaining.enabled": True,
        "pipeline.source-batch-size": batch_size,
        "device.batch-capacity": batch_size,
        "worker.queue-size": queue_mult * batch_size,
    })
    rows: list = []
    g = build(rows, "jax", events, [], [])
    t0 = time.perf_counter()
    run_graph(g, job_id="perf-guard", timeout=600)
    return time.perf_counter() - t0, rows


def test_q8_scaled_parity_throughput_and_batch_count(_storage):
    import bench

    events, batch = 120_000, 8192
    wall, rows = _run(bench.build_q8, events, batch, 1)
    n_rows = bench.check_parity_q8(rows, events)
    assert n_rows > 0
    eps = events / wall
    assert eps > 60_000, f"q8 catastrophically slow: {eps:,.0f} ev/s"
    # 120k events at 100us spacing = 12s = 2 windows; the fused close +
    # coalescing emit one batch per window close (plus slack for the
    # boundary). Per-window de-coalescing would multiply this count.
    n_windows = len({(ts // bench.WIDTH) for b in rows
                     for ts in np.asarray(b["_timestamp"]).tolist()})
    assert len(rows) <= 4 * n_windows + 8, (
        f"{len(rows)} sink batches for {n_windows} windows: emission path "
        f"is de-coalesced")


def test_q5_scaled_parity_throughput_and_batch_count(_storage):
    import bench

    events, batch = 200_000, 8192
    wall, rows = _run(bench.build_q5, events, batch, 2)
    total = bench.check_parity_q5(rows, events)
    assert total > 0
    eps = events / wall
    assert eps > 60_000, f"q5 catastrophically slow: {eps:,.0f} ev/s"
    n_windows = len({ws for b in rows
                     for ws in np.asarray(b["window_start"]).tolist()})
    # fused drain emits at most one batch per watermark-driven close round;
    # well under one batch per window once fusing + coalescing work
    assert len(rows) <= 2 * n_windows + 8, (
        f"{len(rows)} sink batches for {n_windows} windows: emission path "
        f"is de-coalesced")
    mean_rows = sum(b.num_rows for b in rows) / len(rows)
    assert mean_rows >= 64, f"mean emit batch of {mean_rows:.0f} rows"
