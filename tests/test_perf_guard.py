"""Microbench guard (slow): scaled-down q8/q5 through the full engine.

Asserts three things the coalescing work must keep true:
  - exact parity against the bench oracles (scaled event counts),
  - a VERY conservative events/s sanity floor (an order of magnitude under
    the measured numbers on the slowest box, so only a catastrophic
    regression — not scheduler noise — can trip it),
  - a ceiling on the number of emitted sink batches: accidental
    de-coalescing (per-window or per-key tiny emits sneaking back into the
    emission path) multiplies the batch count long before it shows up in
    wall-clock numbers.

Plus (ISSUE 7) the profiler overhead guard: cost attribution is on by
default in production, so the run-loop wrapping must stay under 5% wall
on the same smoke-scale pipelines.

Container-throttling calibration: the ROADMAP notes bench numbers swing
>2x with CPU throttling, so every budget here is judged only after a fixed
numpy kernel confirms the box runs within 2x of the recorded warm-box
constant — on a colder box the whole module SKIPS with the measured
slowdown in the reason (budget failures there are pure noise, not signal).
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# best-of-3 seconds for _calibration_kernel on the warm box these budgets
# were recorded on (2-core container, idle); re-record alongside any budget
# change
WARM_BOX_CALIBRATION_S = 0.09
MAX_SLOWDOWN = 2.0


def _calibration_kernel() -> float:
    """Fixed numpy workload (BLAS matmul + sort — the same primitives the
    engine hot paths lean on); wall seconds, best of 3."""
    rng = np.random.default_rng(0)
    a = rng.standard_normal((512, 512))

    def once() -> float:
        t0 = time.perf_counter()
        for _ in range(6):
            b = a @ a
            np.sort(b, axis=0)
        return time.perf_counter() - t0

    return min(once() for _ in range(3))


_slowdown: float | None = None


def _require_warm_box() -> None:
    global _slowdown
    if _slowdown is None:
        _slowdown = _calibration_kernel() / WARM_BOX_CALIBRATION_S
    if _slowdown > MAX_SLOWDOWN:
        pytest.skip(
            f"box runs {_slowdown:.1f}x slower than the warm-box calibration "
            f"constant ({WARM_BOX_CALIBRATION_S}s kernel): container CPU "
            "throttling makes wall-clock budgets pure noise here")


def _run(build, events, batch_size, queue_mult, job_id="perf-guard"):
    import bench

    from arroyo_tpu import config as cfg
    from arroyo_tpu.engine import run_graph

    cfg.update({
        "pipeline.chaining.enabled": True,
        "pipeline.source-batch-size": batch_size,
        "device.batch-capacity": batch_size,
        "worker.queue-size": queue_mult * batch_size,
    })
    rows: list = []
    g = build(rows, "jax", events, [], [])
    t0 = time.perf_counter()
    run_graph(g, job_id=job_id, timeout=600)
    return time.perf_counter() - t0, rows


def test_q8_scaled_parity_throughput_and_batch_count(_storage):
    import bench

    _require_warm_box()
    events, batch = 120_000, 8192
    wall, rows = _run(bench.build_q8, events, batch, 1)
    n_rows = bench.check_parity_q8(rows, events)
    assert n_rows > 0
    eps = events / wall
    assert eps > 60_000, f"q8 catastrophically slow: {eps:,.0f} ev/s"
    # 120k events at 100us spacing = 12s = 2 windows; the fused close +
    # coalescing emit one batch per window close (plus slack for the
    # boundary). Per-window de-coalescing would multiply this count.
    n_windows = len({(ts // bench.WIDTH) for b in rows
                     for ts in np.asarray(b["_timestamp"]).tolist()})
    assert len(rows) <= 4 * n_windows + 8, (
        f"{len(rows)} sink batches for {n_windows} windows: emission path "
        f"is de-coalesced")


def test_q5_scaled_parity_throughput_and_batch_count(_storage):
    import bench

    _require_warm_box()
    events, batch = 200_000, 8192
    wall, rows = _run(bench.build_q5, events, batch, 2)
    total = bench.check_parity_q5(rows, events)
    assert total > 0
    eps = events / wall
    assert eps > 60_000, f"q5 catastrophically slow: {eps:,.0f} ev/s"
    n_windows = len({ws for b in rows
                     for ws in np.asarray(b["window_start"]).tolist()})
    # fused drain emits at most one batch per watermark-driven close round;
    # well under one batch per window once fusing + coalescing work
    assert len(rows) <= 2 * n_windows + 8, (
        f"{len(rows)} sink batches for {n_windows} windows: emission path "
        f"is de-coalesced")
    mean_rows = sum(b.num_rows for b in rows) / len(rows)
    assert mean_rows >= 64, f"mean emit batch of {mean_rows:.0f} rows"


def test_profiler_overhead_under_5pct(_storage):
    """Cost attribution (obs/profile.py) ships on by default, so the
    self-time wrapping + sketch feed must be noise on a real pipeline.
    Interleaved best-of-3 per mode on smoke-scale q5 decorrelates slow
    box drift from the on/off comparison; a small absolute epsilon covers
    the timer's noise floor at ~1s run lengths."""
    import bench

    from arroyo_tpu import config as cfg
    from arroyo_tpu.metrics import registry

    _require_warm_box()
    events, batch = 100_000, 8192
    best = {True: float("inf"), False: float("inf")}
    try:
        # one throwaway warm run so jit/window compiles don't land on the
        # first measured mode
        _run(bench.build_q5, events, batch, 2, job_id="prof-ovh-warm")
        for _rep in range(3):
            for enabled in (False, True):
                cfg.update({"profile.enabled": enabled})
                registry.clear_job("prof-ovh")
                wall, rows = _run(bench.build_q5, events, batch, 2,
                                  job_id="prof-ovh")
                bench.check_parity_q5(rows, events)
                best[enabled] = min(best[enabled], wall)
    finally:
        cfg.update({"profile.enabled": True})
    overhead = best[True] / best[False] - 1.0
    assert best[True] <= best[False] * 1.05 + 0.10, (
        f"profiling overhead {overhead * 100:.1f}% "
        f"(on {best[True]:.3f}s vs off {best[False]:.3f}s) exceeds the 5% "
        "budget: the run-loop wrapping or sketch feed got expensive")
    # and the profiled run actually attributed the cost somewhere
    jm = registry.job_metrics("prof-ovh")
    assert any(sum((m.get("self_time") or {}).values()) > 0
               for m in jm.values()), "profiling on but no self-time recorded"