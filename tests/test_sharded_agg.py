"""Multi-chip sharded aggregation on a virtual 8-device CPU mesh:
differential against the numpy oracle, plus key-ownership checks."""

import numpy as np
import pytest

from arroyo_tpu.hashing import hash_column, servers_for_hashes
from arroyo_tpu.ops import DeviceHashAggregator
from arroyo_tpu.parallel import ShardedAggregator, make_mesh


def _pad_sharded(n_dev, batch_cap, keys, bins, vals):
    """Scatter a flat stream round-robin across devices, pad to batch_cap."""
    k = np.zeros((n_dev, batch_cap), dtype=np.int64)
    b = np.zeros((n_dev, batch_cap), dtype=np.int32)
    valid = np.zeros((n_dev, batch_cap), dtype=bool)
    vs = [np.zeros((n_dev, batch_cap), dtype=v.dtype) for v in vals]
    for d in range(n_dev):
        rows = slice(d, len(keys), n_dev)
        m = len(keys[rows])
        assert m <= batch_cap
        k[d, :m] = keys[rows].view(np.int64)
        b[d, :m] = bins[rows]
        valid[d, :m] = True
        for i, v in enumerate(vals):
            vs[i][d, :m] = v[rows]
    return k, b, valid, vs


def test_sharded_matches_oracle():
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs multi-device CPU mesh")
    mesh = make_mesh(4)
    rng = np.random.default_rng(7)
    agg = ShardedAggregator(mesh, ("sum", "count"), (np.int64, np.int64),
                            cap=1024, batch_cap=128, per_dest_cap=128,
                            max_probes=32, emit_cap=256)
    ora = DeviceHashAggregator(("sum", "count"), (np.int64, np.int64), backend="numpy")
    for _ in range(4):
        n = 400
        keys = hash_column(rng.integers(0, 60, size=n).astype(np.int64))
        bins = rng.integers(0, 3, size=n).astype(np.int32)
        vals = rng.integers(1, 100, size=n).astype(np.int64)
        ones = np.ones(n, dtype=np.int64)
        ora.update(keys, bins, [vals, ones])
        k, b, valid, vs = _pad_sharded(4, 128, keys, bins, [vals, ones])
        agg.update_sharded(k, b, valid, vs)
    sk, sb, sa = agg.extract_all(0, 10, 10)
    ok, ob, oa = ora.extract(0, 10, 10)
    to_dict = lambda K, B, A: {
        (int(b_), int(k_)): (int(A[0][i]), int(A[1][i]))
        for i, (k_, b_) in enumerate(zip(K.view(np.int64), B))
    }
    assert to_dict(sk, sb, sa) == to_dict(ok, ob, oa)


def test_sharded_entries_live_on_owner_shard():
    """After the all_to_all, each (key) must reside on its range owner."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs multi-device CPU mesh")
    mesh = make_mesh(4)
    agg = ShardedAggregator(mesh, ("count",), (np.int64,), cap=256,
                            batch_cap=64, per_dest_cap=64, max_probes=16,
                            emit_cap=64)
    keys = hash_column(np.arange(100, dtype=np.int64))
    bins = np.zeros(100, dtype=np.int32)
    ones = np.ones(100, dtype=np.int64)
    k, b, valid, vs = _pad_sharded(4, 64, keys, bins, [ones])
    agg.update_sharded(k, b, valid, vs)
    keys_t, bins_t, occ_t = (np.asarray(agg.state[0]), np.asarray(agg.state[1]),
                             np.asarray(agg.state[2]))
    for d in range(4):
        present = keys_t[d][occ_t[d]].view(np.uint64)
        if len(present):
            assert (servers_for_hashes(present, 4) == d).all()
