"""Concurrency auditor (analysis/concurrency_audit.py, LR4xx) + the
runtime lock-order witness (obs/lockorder.py).

Three layers:

1. Fixture-driven rule tests: every rule has a positive AND a negative
   fixture, including the model features the rules lean on (thread-role
   seeding from Thread(target=...), the ``# thread:`` annotation grammar,
   helper-closure lock attribution, waiver justification enforcement).
2. CI gates: the repo-wide audit is clean, deterministically ordered,
   and round-trips through JSON and SARIF.
3. The dynamic cross-check: locks built through ``make_lock`` record
   acquires-while-holding edges at runtime; every edge observed while
   exercising the real queue/network/fleet code must be explained by the
   static LR402 graph — and a deliberately inverted acquire order must
   show up as an unexplained edge (the witness actually watches).

Plus regression locks for the true findings this audit surfaced and
fixed (the _SendBuffer error latch, the EmbeddedWorkerHandle epoch
double-report, FleetManager capacity reads).
"""

from __future__ import annotations

import json
import os
import threading
import time
from types import SimpleNamespace

from arroyo_tpu.analysis import render_json, render_sarif
from arroyo_tpu.analysis.concurrency_audit import (
    RULES,
    audit_concurrency_source,
    static_lock_graph_package,
)
from arroyo_tpu.obs import lockorder

PKG_DIR = os.path.join(os.path.dirname(__file__), "..", "arroyo_tpu")


def ids_of(diags):
    return {d.rule_id for d in diags}


def audit(src: str, relpath: str = "engine/fixture.py"):
    return audit_concurrency_source(src, relpath)


# ------------------------------------------------------------------ LR401


LR401_POS = """
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._t = threading.Thread(target=self._loop, name="pump-loop")

    def _loop(self):
        while True:
            self.count += 1

    def snapshot(self):
        with self._lock:
            return self.count
"""


def test_lr401_unlocked_shared_attr():
    diags = audit(LR401_POS)
    hits = [d for d in diags if d.rule_id == "LR401"]
    assert hits and "Pump.count" in hits[0].message
    assert "pump-loop" in hits[0].message and "caller" in hits[0].message


def test_lr401_negative_common_lock():
    good = LR401_POS.replace(
        "        while True:\n            self.count += 1",
        "        while True:\n            with self._lock:\n"
        "                self.count += 1")
    assert "LR401" not in ids_of(audit(good))


def test_lr401_helper_closure_attribution():
    # the write happens in a private helper whose EVERY same-class call
    # site holds the lock: entry-context fixpoint must attribute it
    src = """
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._t = threading.Thread(target=self._loop, name="pump-loop")

    def _bump(self):
        self.count += 1

    def _loop(self):
        with self._lock:
            self._bump()

    def snapshot(self):
        with self._lock:
            return self.count
"""
    assert "LR401" not in ids_of(audit(src))
    # one unlocked call site breaks the attribution -> finding returns
    leaky = src.replace(
        "    def snapshot(self):\n        with self._lock:\n"
        "            return self.count",
        "    def snapshot(self):\n        self._bump()\n"
        "        return self.count")
    assert "LR401" in ids_of(audit(leaky))


def test_lr401_role_annotation_grammar():
    # no Thread(target=...) in sight: the `# thread: <role>` annotation is
    # the only evidence of a second role (HTTP handler dispatch pattern)
    src = """
class Registry:
    def __init__(self):
        self.entries = {}

    # thread: http-request
    def handle(self, k, v):
        self.entries[k] = v

    def flush(self):
        self.entries = {}
"""
    diags = audit(src)
    hits = [d for d in diags if d.rule_id == "LR401"]
    assert hits and "http-request" in hits[0].message
    # without the annotation there is a single role -> silent
    assert "LR401" not in ids_of(audit(src.replace(
        "    # thread: http-request\n", "")))


def test_lr401_waiver_requires_justification():
    bare = LR401_POS.replace(
        "        self.count = 0",
        "        self.count = 0  # concurrency: single-writer")
    assert "LR401" in ids_of(audit(bare)), "bare waiver must NOT suppress"
    justified = LR401_POS.replace(
        "        self.count = 0",
        "        self.count = 0  # concurrency: single-writer — loop owns "
        "every write; snapshot readers tolerate staleness")
    assert "LR401" not in ids_of(audit(justified))


# ------------------------------------------------------------------ LR402


LR402_CYCLE3 = """
import threading

class A:
    def __init__(self, b: "B"):
        self._lock = threading.Lock()
        self.b = b

    def fa(self):
        with self._lock:
            self.b.fb()

class B:
    def __init__(self, c: "C"):
        self._lock = threading.Lock()
        self.c = c

    def fb(self):
        with self._lock:
            self.c.fc()

class C:
    def __init__(self, a: "A"):
        self._lock = threading.Lock()
        self.a = a

    def fc(self):
        with self._lock:
            self.a.fa()
"""


def test_lr402_three_class_cycle():
    diags = [d for d in audit(LR402_CYCLE3) if d.rule_id == "LR402"]
    assert diags
    assert "A._lock" in diags[0].message and "C._lock" in diags[0].message


def test_lr402_two_class_diamond_is_not_a_cycle():
    # A and C both take B's lock while holding their own: two edges INTO
    # B._lock, none out — a diamond, not a cycle
    src = """
import threading

class A:
    def __init__(self, b: "B"):
        self._lock = threading.Lock()
        self.b = b

    def fa(self):
        with self._lock:
            self.b.fb()

class B:
    def __init__(self):
        self._lock = threading.Lock()

    def fb(self):
        with self._lock:
            pass

class C:
    def __init__(self, b: "B"):
        self._lock = threading.Lock()
        self.b = b

    def fc(self):
        with self._lock:
            self.b.fb()
"""
    assert "LR402" not in ids_of(audit(src))


def test_lr402_nonreentrant_self_reacquire():
    src = """
import threading

class A:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
"""
    diags = [d for d in audit(src) if d.rule_id == "LR402"]
    assert diags and "self-deadlock" in diags[0].message
    # an RLock makes the same shape legal
    assert "LR402" not in ids_of(audit(
        src.replace("threading.Lock()", "threading.RLock()")))


# ------------------------------------------------------------------ LR403


def test_lr403_direct_and_interprocedural():
    direct = """
import time, threading

class W:
    def __init__(self):
        self._lock = threading.Lock()

    def poll(self):
        with self._lock:
            time.sleep(0.5)
"""
    assert "LR403" in ids_of(audit(direct))
    # interprocedural: the sleep lives in a helper reached under the lock
    helper = """
import time, threading

class W:
    def __init__(self):
        self._lock = threading.Lock()

    def _backoff(self):
        time.sleep(0.5)

    def poll(self):
        with self._lock:
            self._backoff()
"""
    diags = [d for d in audit(helper) if d.rule_id == "LR403"]
    # attributed interprocedurally: the helper's sleep line is the site,
    # and the lock it inherits from its call sites is named
    assert diags and "W._lock" in diags[0].message
    assert diags[0].site.endswith(":9")  # the sleep, not the with-block
    # the helper alone (never called under a lock) is fine
    unlocked = helper.replace(
        "        with self._lock:\n            self._backoff()",
        "        self._backoff()")
    assert "LR403" not in ids_of(audit(unlocked))


def test_lr403_condition_wait_is_exempt():
    # Condition.wait RELEASES its underlying lock — holding that same lock
    # at the wait() is the whole point, not a finding
    src = """
import threading

class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)

    def take(self):
        with self._lock:
            while not self._peek():
                self._ready.wait(0.1)

    def _peek(self):
        return True
"""
    assert "LR403" not in ids_of(audit(src))


def test_lr403_subsumes_lr105_module_level():
    # the retired LR105's intraprocedural shape (module-level code) still
    # fires, now under the LR403 id
    bad = (
        "import time\n"
        "def f(self):\n"
        "    with self._lock:\n"
        "        time.sleep(1)\n"
    )
    assert "LR403" in ids_of(audit(bad))
    # nested defs execute later, outside the region (old LR105 negative)
    deferred = (
        "import time\n"
        "def f(self):\n"
        "    with self._lock:\n"
        "        def later():\n"
        "            time.sleep(1)\n"
        "        return later\n"
    )
    assert "LR403" not in ids_of(audit(deferred))
    # a legacy `# lint: waive LR105 — why` keeps suppressing (alias)
    waived = bad.replace(
        "        time.sleep(1)",
        "        # lint: waive LR105 — drain holds the lock on purpose\n"
        "        time.sleep(1)")
    assert "LR403" not in ids_of(audit(waived))


# ------------------------------------------------------------------ LR404


LR404_POS = """
import threading

class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self.slots = 0

    def grant(self):
        if self.slots > 0:
            self.slots -= 1
            return True
        return False

    def release(self):
        with self._lock:
            self.slots += 1
"""


def test_lr404_check_then_act():
    diags = [d for d in audit(LR404_POS) if d.rule_id == "LR404"]
    assert diags and "slots" in diags[0].message
    assert diags[0].severity.name == "WARNING"


def test_lr404_negative_atomic():
    good = LR404_POS.replace(
        "    def grant(self):\n        if self.slots > 0:\n"
        "            self.slots -= 1",
        "    def grant(self):\n        with self._lock:\n"
        "            if self.slots > 0:\n                self.slots -= 1",
    ).replace("            return True\n        return False",
              "                return True\n        return False")
    assert "LR404" not in ids_of(audit(good))


# ----------------------------------------------------------------- gates


def test_rules_registered():
    assert RULES == ("LR401", "LR402", "LR403", "LR404")


def test_repo_audit_clean():
    """CI gate: the whole package is fix-or-waived down to zero."""
    from arroyo_tpu.analysis.repo_lint import lint_paths

    diags = [d for d in lint_paths([PKG_DIR],
                                   root=os.path.dirname(PKG_DIR))
             if d.rule_id in RULES]
    assert diags == [], "concurrency audit found:\n" + "\n".join(
        d.render() for d in diags)


def test_determinism_and_json_shape():
    runs = [audit(LR402_CYCLE3 + LR404_POS) for _ in range(3)]
    assert runs[0] == runs[1] == runs[2] and runs[0]
    assert [d.sort_key() for d in runs[0]] == \
        sorted(d.sort_key() for d in runs[0])
    for rec in json.loads(render_json(runs[0])):
        assert set(rec) == {"rule", "severity", "site", "message", "hint"}
        assert rec["rule"] in RULES


def test_sarif_round_trip():
    """One ERROR (LR401) + one WARN (LR404) through the SARIF renderer:
    levels, rule ids, and physical locations all survive."""
    diags = audit(LR401_POS + LR404_POS)
    levels = {d.rule_id: d for d in diags}
    assert "LR401" in levels and "LR404" in levels
    doc = json.loads(render_sarif(diags))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    results = run["results"]
    by_rule = {}
    for r in results:
        by_rule.setdefault(r["ruleId"], r)
    assert by_rule["LR401"]["level"] == "error"
    assert by_rule["LR404"]["level"] == "warning"
    # path:line sites surface as physical locations with the right line
    loc = by_rule["LR401"]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "engine/fixture.py"
    assert loc["region"]["startLine"] >= 1
    # every emitted ruleId is declared in the tool's rule table
    declared = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert set(by_rule) <= declared


# ------------------------------------------------- runtime witness layer


def test_witness_records_and_catches_inverted_order():
    lockorder.enable(reset=True)
    try:
        a = lockorder.make_lock("Fix.a")
        b = lockorder.make_lock("Fix.b")
        with a:
            with b:
                pass
        assert ("Fix.a", "Fix.b") in lockorder.edges()
        # reentry of the same name records no edge (RLock-style noise)
        r = lockorder.make_lock("Fix.r", kind="rlock")
        with r:
            with r:
                pass
        assert all("Fix.r" not in e for e in lockorder.edges())
        # the deliberately INVERTED order shows up as a second edge that
        # no static graph explains — exactly what the cross-check flags
        with b:
            with a:
                pass
        inv = ("Fix.b", "Fix.a")
        assert inv in lockorder.edges()
        static = static_lock_graph_package()
        assert inv not in static, "fixture edge cannot be in the repo graph"
    finally:
        lockorder.disable()
        lockorder.reset()


def test_witness_edges_subset_of_static_graph(tmp_path):
    """Exercise the real coalescing-send and inbox paths under the witness
    and require ZERO unexplained edges vs the static LR402 graph."""
    lockorder.enable(reset=True)
    try:
        from arroyo_tpu.controller.fleet import FleetManager
        from arroyo_tpu.engine.network import _SendBuffer
        from arroyo_tpu.engine.queues import TaskInbox

        # inbox: put/get through the condition pair (aliases to _lock)
        inbox = TaskInbox(1, row_budget=64)
        inbox.put(0, object())
        inbox.close()
        # send buffer draining into a (faked) conn under both locks —
        # the one real nested acquire in the data plane
        r, w = os.pipe()
        try:
            conn = SimpleNamespace(
                fd=w, _send_lock=lockorder.make_lock(
                    "DataPlaneConn._send_lock"))
            buf = _SendBuffer(conn, max_bytes=1 << 20)
            buf.append((0, 0, 1, 0), 1, b"payload", flush=True)
        finally:
            os.close(r)
            os.close(w)
        # fleet ledger under its RLock
        fleet = FleetManager(None)
        fleet.used_slots()
        fleet.pool_slots()

        observed = lockorder.edges()
        assert ("_SendBuffer._lock", "DataPlaneConn._send_lock") in observed
        static = set(static_lock_graph_package())
        unexplained = {e for e in observed if e not in static}
        assert not unexplained, (
            f"runtime acquire-order edges missing from the static LR402 "
            f"graph: {sorted(unexplained)}")
    finally:
        lockorder.disable()
        lockorder.reset()


def test_lock_contend_fault_site():
    """A lock_contend plan instruments locks built while it is active and
    fires inside the critical section (hold-time delay)."""
    from arroyo_tpu import faults
    from arroyo_tpu.engine.queues import TaskInbox

    faults.install("lock_contend:delay=1@match=TaskInbox")
    try:
        inj = faults.active()
        inbox = TaskInbox(1, row_budget=64)
        assert isinstance(inbox._lock, lockorder._TrackedLock)
        inbox.put(0, object())
        got = inbox.get(timeout=1.0)
        assert got is not None
        assert inj.specs[0].hits > 0, "lock_contend never fired"
    finally:
        faults.clear()


# ------------------------------------- regression locks for fixed bugs


def test_sendbuffer_append_path_latches_errors():
    """The bug LR403/LR401 triage surfaced: a flush failure on the APPEND
    path tore the stream but did not latch _error, so later appends kept
    feeding a half-written connection."""
    from arroyo_tpu.engine.network import _SendBuffer

    r, w = os.pipe()
    os.close(r)
    os.close(w)  # every write now fails EBADF
    conn = SimpleNamespace(fd=w, _send_lock=threading.Lock())
    buf = _SendBuffer(conn, max_bytes=1 << 20)
    try:
        buf.append((0, 0, 1, 0), 1, b"x", flush=True)
        raise AssertionError("write on a closed fd must fail")
    except ConnectionError:
        pass
    assert buf._error is not None, "append-path failure must latch"
    try:
        buf.append((0, 0, 1, 0), 1, b"y", flush=False)
        raise AssertionError("latched buffer must reject later appends")
    except ConnectionError:
        pass


def test_embedded_handle_no_epoch_double_report():
    """_emit_epochs runs on BOTH the worker thread and poll_events; the
    completed-minus-reported window must not double-report an epoch."""
    from arroyo_tpu.controller.scheduler import EmbeddedWorkerHandle
    import queue as _q

    h = EmbeddedWorkerHandle.__new__(EmbeddedWorkerHandle)
    h.engine = SimpleNamespace(
        coordinated=False, job_id="j-dup", _completed_epochs=set())
    h._events = _q.Queue()
    h._reported_epochs = set()
    h._emit_lock = threading.Lock()
    h._last_metrics = time.monotonic() + 3600  # keep metrics quiet

    start = threading.Barrier(3)
    stop = threading.Event()

    def racer():
        start.wait()
        while not stop.is_set():
            h._emit_epochs()

    threads = [threading.Thread(target=racer) for _ in range(2)]
    for t in threads:
        t.start()
    start.wait()
    for ep in range(200):
        h.engine._completed_epochs.add(ep)
    time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(5)
    reported = []
    while True:
        try:
            ev = h._events.get_nowait()
        except _q.Empty:
            break
        if ev["event"] == "checkpoint_completed":
            reported.append(ev["epoch"])
    assert len(reported) == len(set(reported)), "epoch reported twice"


def test_fleet_capacity_reads_take_the_ledger_lock():
    """pool_slots() must synchronize with the background probe thread's
    capacity publish (the fleet LR401 finding)."""
    from arroyo_tpu.controller.fleet import FleetManager

    fleet = FleetManager(None)
    fleet._node_capacity = 7
    acquired = threading.Event()
    release = threading.Event()

    def holder():
        with fleet._lock:
            acquired.set()
            release.wait(5)

    t = threading.Thread(target=holder)
    t.start()
    assert acquired.wait(5)
    got: list = []
    reader = threading.Thread(target=lambda: got.append(fleet.pool_slots()))
    reader.start()
    reader.join(0.2)
    assert reader.is_alive(), "pool_slots must block while the probe lock " \
        "is held (it reads published capacity under the ledger lock)"
    release.set()
    reader.join(5)
    t.join(5)
    assert got == [7]
