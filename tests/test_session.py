"""Session window aggregate: gap merges, per-key isolation, watermark-driven
emission, checkpoint/restore."""

import numpy as np
import pytest

from arroyo_tpu.batch import Batch, TIMESTAMP_FIELD, Schema
from arroyo_tpu.engine import Engine, run_graph
from arroyo_tpu.expr import Col
from arroyo_tpu.graph import EdgeType, Graph, Node, OpName
from arroyo_tpu.operators.base import OperatorContext
from arroyo_tpu.state.tables import TableManager
from arroyo_tpu.types import TaskInfo, Watermark
from arroyo_tpu.windows.session import SessionAggregate

DUMMY = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])


class FakeCollector:
    def __init__(self):
        self.batches = []

    def collect(self, b):
        self.batches.append(b)

    def broadcast(self, s):
        pass


def make_op(gap=1000, key_fields=("u",), aggs=None):
    op = SessionAggregate({
        "gap_micros": gap,
        "key_fields": list(key_fields),
        "aggregates": aggs or [("cnt", "count", None), ("total", "sum", Col("v"))],
        "input_dtype_of": lambda e: np.dtype(np.int64),
    })
    ti = TaskInfo("j", "sess", "session_aggregate", 0, 1)
    ctx = OperatorContext(ti, None, TableManager(ti, "/tmp/unused-session"))
    return op, ctx, FakeCollector()


def keyed_batch(ts, users, vals):
    from arroyo_tpu.hashing import hash_columns

    u = np.array(users, dtype=object)
    return Batch({
        TIMESTAMP_FIELD: np.array(ts, dtype=np.int64),
        "u": u,
        "v": np.array(vals, dtype=np.int64),
        "_key": hash_columns([u]),
    })


def rows_of(col):
    out = []
    for b in col.batches:
        out.extend(b.to_pylist())
    return out


def test_basic_session_merge_and_emit():
    op, ctx, col = make_op(gap=1000)
    # user a: events at 0,500,900 (one session); 3000 (second session)
    # user b: 100 only
    op.process_batch(keyed_batch([0, 500, 900, 3000, 100],
                                 ["a", "a", "a", "a", "b"],
                                 [1, 2, 3, 4, 10]), ctx, col)
    # watermark 1500: no session closed yet (a's first session ends 900+1000=1900,
    # b's ends 100+1000=1100 -> b closes at wm>=1100)
    op.handle_watermark(Watermark.event_time(1100), ctx, col)
    rows = rows_of(col)
    assert len(rows) == 1
    assert rows[0]["u"] == "b" and rows[0]["cnt"] == 1 and rows[0]["total"] == 10
    assert rows[0]["window_start"] == 100 and rows[0]["window_end"] == 1100
    op.handle_watermark(Watermark.event_time(1900), ctx, col)
    rows = rows_of(col)
    assert len(rows) == 2
    a1 = rows[1]
    assert a1["u"] == "a" and a1["cnt"] == 3 and a1["total"] == 6
    assert a1["window_start"] == 0 and a1["window_end"] == 1900
    op.on_close(ctx, col)
    rows = rows_of(col)
    assert len(rows) == 3
    assert rows[2]["u"] == "a" and rows[2]["cnt"] == 1 and rows[2]["total"] == 4
    assert rows[2]["window_start"] == 3000


def test_out_of_order_merges_sessions():
    """An event landing in the gap between two sessions merges them."""
    op, ctx, col = make_op(gap=1000)
    op.process_batch(keyed_batch([0, 2500], ["a", "a"], [1, 2]), ctx, col)
    # two separate sessions so far; 1200 bridges both (0..1000, 1200 in gap
    # of first? 1200 - 0 <= ... session1 max=0, 1200-0>1000 -> no; but
    # 2500-1200>1000 -> no). Use 900 and 1800 to chain-merge everything.
    op.process_batch(keyed_batch([900, 1800], ["a", "a"], [10, 20]), ctx, col)
    op.on_close(ctx, col)
    rows = rows_of(col)
    assert len(rows) == 1
    assert rows[0]["cnt"] == 4 and rows[0]["total"] == 33
    assert rows[0]["window_start"] == 0 and rows[0]["window_end"] == 3500


def test_single_batch_run_splitting():
    """Rows of one batch further apart than the gap split into sessions."""
    op, ctx, col = make_op(gap=100)
    op.process_batch(keyed_batch([0, 50, 400, 450, 1000],
                                 ["a"] * 5, [1, 1, 1, 1, 1]), ctx, col)
    op.on_close(ctx, col)
    rows = rows_of(col)
    assert [(r["window_start"], r["cnt"]) for r in rows] == [(0, 2), (400, 2), (1000, 1)]


def test_min_max_avg_aggregates():
    op, ctx, col = make_op(gap=1000, aggs=[
        ("mn", "min", Col("v")), ("mx", "max", Col("v")), ("av", "avg", Col("v")),
    ])
    op.process_batch(keyed_batch([0, 100, 200], ["a"] * 3, [5, 1, 9]), ctx, col)
    op.on_close(ctx, col)
    r = rows_of(col)[0]
    assert r["mn"] == 1 and r["mx"] == 9 and r["av"] == 5.0


def test_session_checkpoint_restore():
    """Snapshot open sessions, restore into a fresh operator, results match."""
    storage = "/tmp/session-ckpt-test"
    import shutil

    shutil.rmtree(storage, ignore_errors=True)
    ti = TaskInfo("j", "sess", "session_aggregate", 0, 1)
    cfg = {
        "gap_micros": 1000,
        "key_fields": ["u"],
        "aggregates": [("cnt", "count", None), ("total", "sum", Col("v"))],
        "input_dtype_of": lambda e: np.dtype(np.int64),
    }
    op = SessionAggregate(cfg)
    tm = TableManager(ti, storage)
    ctx = OperatorContext(ti, None, tm)
    col = FakeCollector()
    op.process_batch(keyed_batch([0, 500, 3000], ["a", "a", "b"], [1, 2, 3]), ctx, col)
    op.handle_checkpoint(None, ctx, col)
    tm.checkpoint(1, None)

    op2 = SessionAggregate(cfg)
    tm2 = TableManager(ti, storage)
    tm2.restore(1, op2.tables())
    ctx2 = OperatorContext(ti, None, tm2)
    col2 = FakeCollector()
    op2.on_start(ctx2)
    op2.process_batch(keyed_batch([900], ["a"], [10]), ctx2, col2)
    op2.on_close(ctx2, col2)
    rows = sorted(rows_of(col2), key=lambda r: r["u"])
    assert rows[0]["u"] == "a" and rows[0]["cnt"] == 3 and rows[0]["total"] == 13
    assert rows[0]["window_start"] == 0 and rows[0]["window_end"] == 1900
    assert rows[1]["u"] == "b" and rows[1]["cnt"] == 1 and rows[1]["total"] == 3


def test_session_high_key_cardinality():
    """100k+ distinct keys through the array-resident session state: exact
    parity with a brute-force oracle and no per-key interpreter blowup
    (VERDICT r4: nothing pinned behavior at high key counts)."""
    import time as _time

    rng = np.random.default_rng(7)
    n_keys, n_rows = 120_000, 400_000
    keys = rng.integers(0, n_keys, n_rows)
    # bursty per-key times: two bursts per key far enough apart to split
    ts = (keys * 10_000 + rng.integers(0, 3, n_rows) * 200
          + rng.integers(0, 2, n_rows) * 5_000).astype(np.int64)
    vals = rng.integers(1, 100, n_rows).astype(np.int64)
    gap = 1_000

    op = SessionAggregate({
        "gap_micros": gap,
        "key_fields": ["k"],
        "aggregates": [("cnt", "count", None), ("total", "sum", Col("v"))],
        "input_dtype_of": lambda e: np.dtype(np.int64),
    })
    ti = TaskInfo("j", "sess", "session_aggregate", 0, 1)
    ctx = OperatorContext(ti, None, TableManager(ti, "/tmp/unused-session-hk"))
    col = FakeCollector()
    from arroyo_tpu.hashing import hash_columns

    t0 = _time.perf_counter()
    for lo in range(0, n_rows, 50_000):
        hi = min(lo + 50_000, n_rows)
        k = keys[lo:hi]
        op.process_batch(Batch({
            TIMESTAMP_FIELD: ts[lo:hi],
            "k": k,
            "v": vals[lo:hi],
            "_key": hash_columns([k]),
        }), ctx, col)
    op.on_close(ctx, col)
    elapsed = _time.perf_counter() - t0
    # oracle: brute-force session merge on (key, sorted ts)
    order = np.lexsort((ts, keys))
    ks, tss, vs = keys[order], ts[order], vals[order]
    want = {}
    i0 = 0
    for i in range(1, n_rows + 1):
        if i == n_rows or ks[i] != ks[i - 1] or tss[i] - tss[i - 1] > gap:
            want[(int(ks[i0]), int(tss[i0]))] = (i - i0, int(vs[i0:i].sum()))
            i0 = i
    got = {}
    for b in col.batches:
        kk = np.asarray(b["k"])
        ws = np.asarray(b["window_start"])
        cnt = np.asarray(b["cnt"])
        tot = np.asarray(b["total"])
        for i in range(b.num_rows):
            got[(int(kk[i]), int(ws[i]))] = (int(cnt[i]), int(tot[i]))
    assert got == want
    # vectorized merge: the whole 400k-row / 120k-key run stays fast; the
    # old per-key Python path took minutes at this cardinality
    assert elapsed < 30.0


def test_session_end_to_end_graph():
    """Pipeline run: impulse with bursty timing via projection is complex, so
    use vec-source style via single-key sessions over impulse gaps."""
    rows: list = []
    g = Graph()
    # impulse: 100 events, 1ms apart -> with gap 10ms all merge to 1 session
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "impulse", "message_count": 100,
        "interval_micros": 1000, "start_time_micros": 0}, 1))
    g.add_node(Node("wm", OpName.WATERMARK, {"expr": Col(TIMESTAMP_FIELD)}, 1))
    g.add_node(Node("agg", OpName.SESSION_AGGREGATE, {
        "gap_micros": 10_000,
        "key_fields": [],
        "aggregates": [("cnt", "count", None)],
    }, 1))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "wm", EdgeType.FORWARD, DUMMY)
    g.add_edge("wm", "agg", EdgeType.FORWARD, DUMMY)
    g.add_edge("agg", "sink", EdgeType.FORWARD, DUMMY)
    run_graph(g, job_id="sess-e2e", timeout=60)
    assert len(rows) == 1
    assert rows[0]["cnt"] == 100
    assert rows[0]["window_start"] == 0
    assert rows[0]["window_end"] == 99_000 + 10_000
