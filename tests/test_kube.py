"""Kubernetes scheduler (controller/kube.py) against a fake kube-apiserver:
pod creation with the node-id correlation env, full job lifecycle through
the pod's node daemon, pod deletion on kill/finish.
Reference: arroyo-controller/src/schedulers/kubernetes/mod.rs."""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest


class FakeKubeApi(threading.Thread):
    """Accepts pod create/delete; 'runs' each created pod by starting an
    in-process NodeServer with the pod's injected node id."""

    def __init__(self, cluster_api_base: str):
        super().__init__(daemon=True)
        self.cluster_api_base = cluster_api_base
        self.pods: dict[str, dict] = {}
        self.created: list[dict] = []
        self.nodes: dict[str, object] = {}
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code, payload):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                manifest = json.loads(self.rfile.read(n))
                name = manifest["metadata"]["name"]
                outer.pods[name] = manifest
                outer.created.append(manifest)
                outer._start_pod(name, manifest)
                self._json(201, manifest)

            def do_DELETE(self):
                name = self.path.rsplit("/", 1)[1]
                outer._stop_pod(name)
                self._json(200, {})

            def do_GET(self):
                name = self.path.rsplit("/", 1)[1]
                if name in outer.pods:
                    self._json(200, outer.pods[name])
                else:
                    self._json(404, {"error": "notfound"})

        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.srv.server_address[1]
        self.base_url = f"http://127.0.0.1:{self.port}"

    def _start_pod(self, name, manifest):
        from arroyo_tpu import config as cfg
        from arroyo_tpu.controller.node import NodeServer

        env = {e["name"]: e.get("value") for e in
               manifest["spec"]["containers"][0]["env"] if "value" in e}
        node_id = env["ARROYO_TPU__NODE__ID"]
        cfg.update({"node.id": node_id})
        try:
            self.nodes[name] = NodeServer(self.cluster_api_base, slots=1).start()
        finally:
            cfg.update({"node.id": None})

    def _stop_pod(self, name):
        self.pods.pop(name, None)
        node = self.nodes.pop(name, None)
        if node is not None:
            node.stop()

    def run(self):
        self.srv.serve_forever()

    def close(self):
        self.srv.shutdown()


def test_kubernetes_scheduler_lifecycle(tmp_path, _storage):
    from arroyo_tpu import config as cfg
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.kube import KubeClient, KubernetesScheduler

    os.environ["ARROYO_TPU__CHECKPOINT__STORAGE_URL"] = cfg.config().get(
        "checkpoint.storage-url")
    inp = tmp_path / "in.json"
    with open(inp, "w") as f:
        for i in range(100):
            f.write(json.dumps({"x": i, "timestamp": i * 1000}) + "\n")
    out_path = tmp_path / "out.json"
    sql = f"""
CREATE TABLE src (timestamp TIMESTAMP, x BIGINT)
WITH (connector = 'single_file', path = '{inp}', format = 'json', type = 'source', event_time_field = 'timestamp');
CREATE TABLE snk (x BIGINT, t BIGINT)
WITH (connector = 'single_file', path = '{out_path}', format = 'json', type = 'sink');
INSERT INTO snk SELECT x, x * 3 AS t FROM src;
"""
    db = Database()
    api = ApiServer(db).start()
    fake = FakeKubeApi(f"http://127.0.0.1:{api.port}")
    fake.start()
    # conftest's autouse _storage fixture cfg.reset()s per test, so these
    # process-global updates cannot leak across tests
    cfg.update({"kubernetes-scheduler.namespace": "test-ns",
                "kubernetes-scheduler.image": "arroyo-tpu:test",
                "kubernetes-scheduler.pod-startup-timeout-s": 30})
    sched = KubernetesScheduler(db, KubeClient(base_url=fake.base_url))
    ctl = ControllerServer(db, sched).start()
    try:
        pid = db.create_pipeline("kpipe", sql, 1)
        jid = db.create_job(pid)
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        rows = [json.loads(l) for l in open(out_path)]
        assert len(rows) == 100 and all(r["t"] == r["x"] * 3 for r in rows)
        # exactly one pod was created, carrying the correlation env and the
        # configured image, and it was deleted after the job finished
        assert len(fake.created) == 1
        manifest = fake.created[0]
        cont = manifest["spec"]["containers"][0]
        env = {e["name"]: e.get("value") for e in cont["env"] if "value" in e}
        assert cont["image"] == "arroyo-tpu:test"
        assert env["ARROYO_TPU__NODE__ID"].startswith("node_")
        assert manifest["metadata"]["labels"]["app"] == "arroyo-tpu-worker"
        deadline = time.time() + 10
        while fake.pods and time.time() < deadline:
            time.sleep(0.1)
        assert not fake.pods, "pod not deleted after job finished"
    finally:
        os.environ.pop("ARROYO_TPU__CHECKPOINT__STORAGE_URL", None)
        ctl.stop()
        fake.close()
        api.stop()


def test_kubernetes_pod_never_registers_times_out(_storage):
    from arroyo_tpu import config as cfg
    from arroyo_tpu.controller import Database
    from arroyo_tpu.controller.kube import KubeClient, KubernetesScheduler

    class NullKube(KubeClient):
        def __init__(self):
            self.deleted = []
            super().__init__(base_url="http://127.0.0.1:1")

        def create_pod(self, namespace, manifest):
            return manifest  # accepted but nothing ever starts

        def delete_pod(self, namespace, name):
            self.deleted.append(name)

        def pod_phase(self, namespace, name):
            return "Pending"

    cfg.update({"kubernetes-scheduler.pod-startup-timeout-s": 1})
    kube = NullKube()
    sched = KubernetesScheduler(Database(), kube)
    # start_worker is non-blocking now: it returns a pending handle whose
    # poll_events declares failure once the startup deadline passes
    handle = sched.start_worker("SELECT 1", "job_x", 1, None)
    deadline = time.time() + 10
    events = []
    while not events and time.time() < deadline:
        events = handle.poll_events()
        time.sleep(0.1)
    assert events and events[0]["event"] == "failed"
    assert "never registered" in events[0]["error"]
    assert len(kube.deleted) == 1  # the orphaned pod is cleaned up
    assert not handle.alive()


def test_manifest_probes_and_autoscaler_keys():
    """k8s/arroyo-tpu.yaml must carry liveness/readiness probes on both
    tiers (API ping for the control plane, /status for node daemons) and
    enable the elastic autoscaler with explicit bounds — the manifest is
    documentation-grade and this keeps it from regressing to dead weight."""
    path = os.path.join(os.path.dirname(__file__), "..", "k8s",
                        "arroyo-tpu.yaml")
    with open(path) as f:
        text = f.read()
    assert text.count("livenessProbe") == 2
    assert text.count("readinessProbe") == 2
    assert "/api/v1/ping" in text
    assert "/status" in text
    assert "ARROYO_TPU__AUTOSCALER__ENABLED" in text
    assert "ARROYO_TPU__AUTOSCALER__MAX_PARALLELISM" in text
    # multi-tenant fleet: per-tenant quotas, the per-job supervision tick
    # budget, and the node-pool scaling knob (fleet elasticity) must ride
    # the control-plane deployment
    assert "ARROYO_TPU__FLEET__QUOTA__MAX_SLOTS" in text
    assert "ARROYO_TPU__FLEET__TICK_BUDGET_MS" in text
    assert "ARROYO_TPU__FLEET__AUTOSCALE__ENABLED" in text
    assert "arroyo_fleet_target_workers" in text, (
        "the manifest must name the gauge an external node-pool "
        "autoscaler keys off")
    readme = os.path.join(os.path.dirname(path), "README.md")
    assert os.path.exists(readme)
    assert "Multi-tenant fleet" in open(readme).read()
