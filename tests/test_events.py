"""The third observability pillar: structured job events + health monitors.

Covers obs/events.py (bounded per-job ring, level/seq/since filters,
stdlib-logging bridge, rendering) and obs/health.py (rule set with
hysteresis) at unit level, then end-to-end: an operator exception becomes
an OPERATOR_PANIC event with the right scope; a worker crash mid-checkpoint
on a 2-worker set leaves a causally ordered ERROR -> RESTORE trail readable
from the controller DB, the API, and the `logs` CLI, with the same epoch's
events rendered as instants in the Chrome trace export; a dropped phase-2
commit proves the worker->controller {"event": "log"} relay over the real
process-scheduler wire protocol; and a sustained watermark-lag breach
drives ok -> degraded visibly in `top`, `/health`, and `arroyo_job_health`.
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
import urllib.request

import pytest

from arroyo_tpu.obs import events as obs_events
from arroyo_tpu.obs import health as obs_health
from arroyo_tpu.obs.events import recorder

SMOKE = os.path.join(os.path.dirname(__file__), "smoke")


def _sql(tmp_path, name="grouped_aggregates"):
    with open(os.path.join(SMOKE, "queries", f"{name}.sql")) as f:
        sql = f.read()
    out = str(tmp_path / "out.json")
    return sql.replace("$input_dir", os.path.join(SMOKE, "inputs")).replace(
        "$output_path", out), out


def _assert_golden(out, name="grouped_aggregates"):
    import glob

    got = []
    for p in sorted(glob.glob(out) + glob.glob(out + ".*")):
        with open(p) as f:
            got.extend(json.loads(l) for l in f if l.strip())
    with open(os.path.join(SMOKE, "golden", f"{name}.json")) as f:
        want = [json.loads(l) for l in f if l.strip()]
    key = lambda r: json.dumps(r, sort_keys=True)
    assert sorted(map(key, got)) == sorted(map(key, want))


# ------------------------------------------------------------ ring, unit


def test_event_ring_bounds_under_flood_but_counts_stay_exact():
    from arroyo_tpu import config as cfg

    job = "flood-job"
    recorder.clear_job(job)
    cfg.update({"obs.events.max-per-job": 64})
    try:
        for i in range(500):
            recorder.record(job, "INFO", "LOG", message=f"m{i}")
        ring = recorder.events(job)
        # the ring is bounded and keeps the NEWEST events, seq-ordered
        assert len(ring) == 64
        assert [e["seq"] for e in ring] == list(range(437, 501))
        assert ring[-1]["message"] == "m499"
        # totals survive eviction (the arroyo_events_total surface)
        assert recorder.counts_snapshot()[(job, "LOG", "INFO")] == 500
        assert recorder.last_seq(job) == 500
    finally:
        cfg.update({"obs.events.max-per-job": 512})
        recorder.clear_job(job)
    assert recorder.events(job) == []
    assert all(k[0] != job for k in recorder.counts_snapshot())


def test_event_filters_render_and_trail():
    job = "filter-job"
    recorder.clear_job(job)
    try:
        recorder.record(job, "DEBUG", "LOG", message="noise")
        recorder.record(job, "ERROR", "OPERATOR_PANIC", message="div by zero",
                        node="agg", subtask=1, epoch=3,
                        data={"digest": "abc123def456"})
        recorder.record(job, "WARN", "RESTORE", message="restoring", epoch=2,
                        worker=0)
        # level is a minimum: WARN returns WARN + ERROR, seq order kept
        warn_up = recorder.events(job, level="WARN")
        assert [e["code"] for e in warn_up] == ["OPERATOR_PANIC", "RESTORE"]
        # seq cursor (the logs --follow / ?after= contract)
        assert [e["code"] for e in recorder.events(job, after_seq=2)] \
            == ["RESTORE"]
        # unknown levels normalize instead of corrupting the ring
        ev = recorder.record(job, "fatal?!", "LOG")
        assert ev["level"] == "INFO"
        # one rendered CLI line carries level, code, scope, message, data
        line = obs_events.render_event(warn_up[0])
        assert "ERROR" in line and "OPERATOR_PANIC" in line
        assert "agg/1" in line and "e3" in line and "div by zero" in line
        assert "abc123def456" in line
        # the causal projection chaos tests assert against
        assert obs_events.trail(warn_up) == ["OPERATOR_PANIC", "RESTORE"]
    finally:
        recorder.clear_job(job)


def test_ingest_preserves_relayed_identity():
    """The controller replays worker-relayed events through ingest():
    original timestamp/level/code/scope survive; seq is reassigned
    locally; junk is rejected rather than recorded."""
    job = "ingest-job"
    recorder.clear_job(job)
    try:
        ev = recorder.ingest(job, {
            "seq": 777, "ts_us": 123_000_000, "level": "WARN",
            "code": "COMMIT_REDELIVERED", "worker": 1, "epoch": 4,
            "message": "late commit"})
        assert ev["ts_us"] == 123_000_000 and ev["level"] == "WARN"
        assert ev["worker"] == 1 and ev["epoch"] == 4
        assert ev["seq"] == 1  # local seq, not the relayed one
        assert recorder.ingest(job, {"event": "not-a-job-event"}) is None
        assert recorder.ingest(job, "garbage") is None
        assert len(recorder.events(job)) == 1
    finally:
        recorder.clear_job(job)


def test_restarted_controller_resumes_past_persisted_seqs(tmp_path):
    """A controller restart empties the in-memory ring (seq restarts at 1)
    while the DB keeps rows keyed (job, seq); re-adoption must seed the
    ring's seq past the persisted max or every new event would collide
    with an old row and be dropped by the idempotent flush."""
    from arroyo_tpu.controller import Database

    job = "seq-floor-job"
    db = Database(str(tmp_path / "ctl.db"))
    recorder.clear_job(job)
    try:
        for i in range(3):
            recorder.record(job, "INFO", "LOG", message=f"before {i}")
        db.record_events(job, recorder.events(job))
        assert db.last_event_seq(job) == 3
        # "restart": the ring is gone, the DB is not
        recorder.clear_job(job)
        recorder.ensure_seq_floor(job, db.last_event_seq(job))
        ev = recorder.record(job, "WARN", "RESTORE", message="after restart")
        assert ev["seq"] == 4  # no collision with the persisted rows
        db.record_events(job, [ev])
        assert [e["message"] for e in db.list_events(job)] \
            == ["before 0", "before 1", "before 2", "after restart"]
        # re-flushing the same seq stays idempotent (skip, not duplicate)
        db.record_events(job, [ev])
        assert db.last_event_seq(job) == 4
        assert len(db.list_events(job)) == 4
    finally:
        recorder.clear_job(job)


def test_logs_cli_errors_on_unknown_job(tmp_path, capsys):
    from arroyo_tpu import cli
    from arroyo_tpu.controller import Database

    db_path = str(tmp_path / "ctl.db")
    Database(db_path)
    assert cli.main(["logs", "no-such-job", "--db", db_path]) == 1
    assert "no such job" in capsys.readouterr().err
    # --follow must not tail a typo forever
    assert cli.main(["logs", "no-such-job", "--db", db_path,
                     "--follow"]) == 1


def test_traceback_digest_stable_and_compact():
    tb = ("Traceback (most recent call last):\n"
          "  File \"x.py\", line 1, in f\n"
          "ZeroDivisionError: division by zero\n")
    d1, d2 = obs_events.traceback_digest(tb), obs_events.traceback_digest(tb)
    assert d1 == d2  # repeated panics of the same bug aggregate
    assert d1["error"] == "ZeroDivisionError: division by zero"
    assert len(d1["digest"]) == 12
    assert obs_events.traceback_digest(tb + "  extra frame\n") != d1


# ------------------------------------------------------- logging bridge


def test_logging_bridge_captures_job_scoped_records_only():
    job = "bridge-job"
    recorder.clear_job(job)
    log = logging.getLogger("arroyo_tpu.test_bridge")
    log.setLevel(logging.INFO)
    log.propagate = False
    handler = obs_events.JobEventBridgeHandler()
    log.addHandler(handler)
    try:
        log.warning("spill started", extra={"job_id": job, "node": "agg",
                                            "subtask": 2})
        log.error("custom", extra={"job_id": job, "event_code": "RESCALE"})
        log.info("service-level line with no job context")  # not captured
        evs = recorder.events(job)
        assert len(evs) == 2
        assert evs[0]["code"] == "LOG" and evs[0]["level"] == "WARN"
        assert evs[0]["node"] == "agg" and evs[0]["subtask"] == 2
        assert evs[0]["message"] == "spill started"
        assert evs[1]["code"] == "RESCALE" and evs[1]["level"] == "ERROR"
    finally:
        log.removeHandler(handler)
        recorder.clear_job(job)


def test_init_logging_capture_events_installs_bridge_idempotently():
    from arroyo_tpu.server_common import init_logging

    root = logging.getLogger()
    saved = list(root.handlers)
    job = "capture-job"
    recorder.clear_job(job)
    try:
        init_logging(fmt="console", capture_events=True)
        bridges = [h for h in root.handlers
                   if isinstance(h, obs_events.JobEventBridgeHandler)]
        assert len(bridges) == 1
        # re-init does not stack a second bridge
        assert obs_events.install_bridge(root) is bridges[0]
        logging.getLogger("arroyo_tpu.capture").warning(
            "wedged?", extra={"job_id": job, "epoch": 9})
        evs = recorder.events(job)
        assert len(evs) == 1 and evs[0]["epoch"] == 9
    finally:
        root.handlers[:] = saved
        recorder.clear_job(job)


def _parse_logfmt(line: str) -> dict:
    out = {}
    for m in re.finditer(r'(\w+)=("(?:[^"\\]|\\.)*"|\S+)', line):
        v = m.group(2)
        if v.startswith('"'):
            v = v[1:-1].replace('\\"', '"')
        out[m.group(1)] = v
    return out


def test_json_and_logfmt_formatters_share_one_field_set():
    """One record carrying event code + scope renders through BOTH
    structured formatters with identical names and values (modulo logfmt's
    lowercase level and msg= spelling) — the shared `_record_fields`
    extraction means the two formats cannot drift."""
    from arroyo_tpu.server_common import _JsonFormatter, _LogfmtFormatter

    record = logging.LogRecord("arroyo_tpu.controller", logging.WARNING,
                               "x.py", 1, "epoch %d wedged", (7,), None)
    record.job_id = "j-1"
    record.event_code = "EPOCH_WEDGED"
    record.node = "agg"
    record.subtask = 0
    record.epoch = 7
    as_json = json.loads(_JsonFormatter().format(record))
    as_logfmt = _parse_logfmt(_LogfmtFormatter().format(record))
    assert as_json["code"] == "EPOCH_WEDGED"
    assert as_json["message"] == "epoch 7 wedged"
    # logfmt spells message as msg= and lowercases the level; every other
    # shared field must match the json rendering exactly
    assert as_logfmt["msg"] == as_json["message"]
    assert as_logfmt["level"] == as_json["level"].lower() == "warning"
    for field in ("ts", "target", "code", "job_id", "node", "subtask",
                  "epoch"):
        assert str(as_json[field]) == as_logfmt[field], field
    # a message containing '=' (but no space) must be quoted, or logfmt
    # parsers would read `msg=retries=3` as a bogus extra key
    eq = logging.LogRecord("t", logging.INFO, "x.py", 1, "retries=3",
                           (), None)
    assert 'msg="retries=3"' in _LogfmtFormatter().format(eq)
    # newlines must never split one record across physical lines
    nl = logging.LogRecord("t", logging.INFO, "x.py", 1, "bad\nthing",
                           (), None)
    line = _LogfmtFormatter().format(nl)
    assert "\n" not in line and 'msg="bad\\nthing"' in line


# ------------------------------------------------------ health, unit


def _snap(**per_op):
    return {op: vals for op, vals in per_op.items()}


def test_health_hysteresis_does_not_flap_on_oscillation():
    from arroyo_tpu import config as cfg

    cfg.update({"health.fire-ticks": 3, "health.clear-ticks": 2})
    transitions = []
    try:
        mon = obs_health.HealthMonitor(
            "h-job", on_transition=lambda o, n, d: transitions.append((o, n)))
        # a metric oscillating around the threshold every tick never fires
        for i in range(20):
            bp = 0.95 if i % 2 == 0 else 0.5  # threshold 0.9
            mon.evaluate(_snap(agg={"backpressure": bp}))
        assert mon.state == "ok" and transitions == []
        # three consecutive breaching ticks fire the rule — exactly once
        for _ in range(3):
            detail = mon.evaluate(_snap(agg={"backpressure": 0.95}))
        assert mon.state == "degraded"
        assert transitions == [("ok", "degraded")]
        assert mon.firing_rules() == ["backpressure"]
        rule = next(r for r in detail["rules"] if r["rule"] == "backpressure")
        assert rule["firing"] and rule["value"] == 0.95
        assert rule["threshold"] == pytest.approx(0.9)
        # one healthy tick does NOT clear (clear-ticks=2)…
        mon.evaluate(_snap(agg={"backpressure": 0.1}))
        assert mon.state == "degraded"
        # …and a breach in between restarts the healthy count
        mon.evaluate(_snap(agg={"backpressure": 0.95}))
        mon.evaluate(_snap(agg={"backpressure": 0.1}))
        assert mon.state == "degraded"
        mon.evaluate(_snap(agg={"backpressure": 0.1}))
        assert mon.state == "ok"
        assert transitions == [("ok", "degraded"), ("degraded", "ok")]
    finally:
        cfg.update({"health.fire-ticks": 3, "health.clear-ticks": 5})


def test_health_checkpoint_streak_is_critical_and_absent_metrics_are_healthy():
    from arroyo_tpu import config as cfg

    cfg.update({"health.fire-ticks": 2, "health.clear-ticks": 2})
    try:
        mon = obs_health.HealthMonitor("h-crit")
        # missing metrics (pre-first-batch) evaluate healthy, not unknown
        assert mon.evaluate(None)["state"] == "ok"
        assert mon.evaluate(_snap(agg={"backpressure": None}))["state"] == "ok"
        for _ in range(2):
            detail = mon.evaluate(None, ckpt_failures=3)
        assert mon.state == "critical"
        assert detail["state"] == "critical"
        # worst firing severity wins: degraded rule + critical rule
        mon2 = obs_health.HealthMonitor("h-mix")
        for _ in range(2):
            d = mon2.evaluate(_snap(agg={"watermark_lag_seconds": 1e6}),
                              ckpt_failures=5)
        assert d["state"] == "critical"
        firing = {r["rule"] for r in d["rules"] if r["firing"]}
        assert firing == {"watermark-lag", "checkpoint-failures"}
    finally:
        cfg.update({"health.fire-ticks": 3, "health.clear-ticks": 5})
    assert obs_health.health_value("ok") == 0
    assert obs_health.health_value("critical") == 2
    assert obs_health.health_event_code("degraded") == "HEALTH_DEGRADED"


# --------------------------------------- operator panic, engine level


def test_operator_exception_becomes_scoped_panic_event(tmp_path, _storage):
    """A task raising in the run loop records OPERATOR_PANIC — naming the
    node/subtask, the epoch (the injected crash fires mid-checkpoint), and
    a stable traceback digest — BEFORE the failure propagates."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.expr import Col
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    src = tmp_path / "in.json"
    with open(src, "w") as f:
        for i in range(500):
            f.write(json.dumps({"x": i, "_timestamp": i * 1000}) + "\n")
    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    rows: list = []
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "single_file", "path": str(src), "schema": S}, 1))
    g.add_node(Node("wm", OpName.WATERMARK, {
        "expr": Col(TIMESTAMP_FIELD), "interval_micros": 1000}, 1))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "wm", EdgeType.FORWARD, S)
    g.add_edge("wm", "sink", EdgeType.FORWARD, S)

    job = "panic-scope"
    recorder.clear_job(job)
    cfg.update({"testing.source-read-delay-micros": 2000})
    faults.install("worker:crash@barrier=1&step=1", seed=5)
    eng = Engine(g, job_id=job)
    try:
        eng.start()
        eng.trigger_checkpoint(1)
        with pytest.raises(RuntimeError):
            eng.join(timeout=60)
    finally:
        faults.clear()
        cfg.update({"testing.source-read-delay-micros": 0})
        eng.stop()

    panics = [e for e in recorder.events(job) if e["code"] == "OPERATOR_PANIC"]
    assert panics, recorder.events(job)
    ev = panics[0]
    assert ev["level"] == "ERROR"
    assert ev["node"] is not None and ev["subtask"] is not None
    assert ev["epoch"] == 1  # the crash fired mid-checkpoint
    assert re.fullmatch(r"[0-9a-f]{12}", ev["data"]["digest"])
    assert "InjectedCrash" in ev["message"]
    recorder.clear_job(job)


# ------------------------------------------- chaos trail, end to end


@pytest.mark.chaos
def test_chaos_crash_leaves_causal_event_trail(tmp_path, _storage, capsys):
    """Acceptance: a worker crash mid-checkpoint on a 2-worker set yields —
    via the controller DB, GET /jobs/<id>/events, and `arroyo_tpu logs` —
    a causally ordered ERROR (OPERATOR_PANIC/WORKER_LOST) -> WARN RESTORE
    trail naming the epoch/worker/subtask; the same epoch's events appear
    as instant markers in the Chrome trace export; goldens stay byte-exact."""
    from arroyo_tpu import cli
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler

    sql, out = _sql(tmp_path)
    db_path = str(tmp_path / "ctl.db")
    db = Database(db_path)
    cfg.update({
        "controller.workers-per-job": 2,
        "checkpoint.interval-ms": 150,
        # generous runway: the crash installs only after the first complete
        # epoch, and the next periodic barrier must still beat EOF (this
        # box throttles hard — a short run can finish before the fault)
        "testing.source-read-delay-micros": 10000,
    })
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    api = ApiServer(db, port=0).start()
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        # let one epoch complete first, so the crash restores from a real
        # checkpoint (a deterministic, non-None restore epoch in the trail)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not any(
                c["state"] == "complete" for c in db.list_checkpoints(jid)):
            time.sleep(0.05)
        assert any(c["state"] == "complete" for c in db.list_checkpoints(jid))
        # crash one subtask at its NEXT barrier, mid-checkpoint
        inj = faults.install("worker:crash@step=1", seed=23)
        state = ctl.wait_for_state(jid, "Finished", timeout=180)
        assert state == "Finished"
        assert int(db.get_job(jid)["restarts"]) >= 1
        assert inj.fired_log, "crash fault never fired"

        # --- the trail, from the persisted DB table -------------------
        evs = db.list_events(jid)
        trail = obs_events.trail(evs)
        assert "RESTORE" in trail, trail
        errors = [e for e in evs if e["level"] == "ERROR"]
        assert errors, trail
        # causal order: the crash ERROR strictly precedes the RESTORE
        first_restore = trail.index("RESTORE")
        first_error = next(i for i, e in enumerate(evs)
                           if e["level"] == "ERROR")
        assert first_error < first_restore, trail
        # scope: the panic names its node/subtask + mid-checkpoint epoch,
        # the loss names the worker, the restore names the restore epoch
        panic = next(e for e in evs if e["code"] == "OPERATOR_PANIC")
        assert panic["node"] is not None and panic["subtask"] is not None
        assert panic["epoch"] is not None  # the crash fired mid-checkpoint
        lost = next(e for e in evs if e["code"] == "WORKER_LOST")
        assert lost["worker"] is not None
        restore = next(e for e in evs if e["code"] == "RESTORE")
        # the crashed epoch never went durable: the set restored from an
        # earlier, globally complete one
        assert restore["epoch"] is not None
        assert restore["epoch"] < panic["epoch"]
        assert restore["data"]["restarts"] >= 1

        # --- the same trail over the API, with level filtering --------
        base = f"http://127.0.0.1:{api.port}"
        with urllib.request.urlopen(
                f"{base}/api/v1/jobs/{jid}/events?level=ERROR",
                timeout=10) as r:
            api_errors = json.loads(r.read())["data"]
        assert api_errors and all(e["level"] == "ERROR" for e in api_errors)
        assert {e["code"] for e in api_errors} \
            <= {"OPERATOR_PANIC", "WORKER_LOST"}

        # --- the logs CLI renders it (DB and API paths) ---------------
        assert cli.main(["logs", jid, "--db", db_path]) == 0
        text = capsys.readouterr().out
        assert "OPERATOR_PANIC" in text and "RESTORE" in text
        assert cli.main(["logs", jid, "--api", base, "--level", "ERROR"]) == 0
        text = capsys.readouterr().out
        assert "WORKER_LOST" in text and "RESTORE" not in text

        # --- epoch-scoped events appear as trace instants -------------
        with urllib.request.urlopen(
                f"{base}/api/v1/jobs/{jid}/traces", timeout=10) as r:
            chrome = json.loads(r.read())
        instants = [e for e in chrome["traceEvents"] if e["cat"] == "events"]
        assert any(e["name"] == "OPERATOR_PANIC" for e in instants), instants
        panic_i = next(e for e in instants if e["name"] == "OPERATOR_PANIC")
        assert panic_i["ph"] == "i"
        assert panic_i["args"]["epoch"] == panic["epoch"]
        assert panic_i["tid"] == f"{panic['node']}/{panic['subtask']}"
    finally:
        faults.clear()
        cfg.update({"controller.workers-per-job": 1,
                    "checkpoint.interval-ms": 10_000,
                    "testing.source-read-delay-micros": 0})
        ctl.stop()
        api.stop()
    _assert_golden(out)


@pytest.mark.chaos
def test_process_worker_relays_events_over_wire(tmp_path, _storage, capsys):
    """Worker->controller relay on the REAL wire protocol: subprocess
    workers of a 2-worker process-scheduler set record COMMIT_REDELIVERED
    in their own process (the controller drops phase-2 commits for epoch 1;
    cumulative delivery recovers them at epoch 2) and relay the events as
    {"event": "log"} JSON lines; the controller ingests, persists, and
    serves them through the API and the logs CLI."""
    from arroyo_tpu import cli
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import ProcessScheduler

    sql, out = _sql(tmp_path)
    db_path = str(tmp_path / "ctl.db")
    db = Database(db_path)
    os.environ["ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS"] = "8000"
    os.environ["ARROYO_TPU__CHECKPOINT__STORAGE_URL"] = cfg.config().get(
        "checkpoint.storage-url")
    cfg.update({"controller.workers-per-job": 2,
                "checkpoint.interval-ms": 300})
    # the drop fires in THIS (controller) process at commit fan-out; the
    # workers' cumulative re-delivery is what generates the relayed events
    inj = faults.install("commit:drop@epoch=1", seed=11)
    ctl = ControllerServer(db, ProcessScheduler()).start()
    api = ApiServer(db, port=0).start()
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        state = ctl.wait_for_state(jid, "Finished", timeout=180)
        assert state == "Finished"
        assert inj.fired_log, "commit drop never fired"

        evs = db.list_events(jid)
        redelivered = [e for e in evs if e["code"] == "COMMIT_REDELIVERED"]
        assert redelivered, [e["code"] for e in evs]
        # the event crossed the wire carrying its worker-side scope
        assert all(e["epoch"] == 1 for e in redelivered)
        workers = {e["worker"] for e in redelivered}
        assert workers and workers <= {0, 1}
        assert all(e["level"] == "WARN" for e in redelivered)

        base = f"http://127.0.0.1:{api.port}"
        with urllib.request.urlopen(
                f"{base}/api/v1/jobs/{jid}/events?level=WARN",
                timeout=10) as r:
            api_evs = json.loads(r.read())["data"]
        assert any(e["code"] == "COMMIT_REDELIVERED" for e in api_evs)

        assert cli.main(["logs", jid, "--api", base]) == 0
        assert "COMMIT_REDELIVERED" in capsys.readouterr().out
        _assert_golden(out)
    finally:
        os.environ.pop("ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS", None)
        os.environ.pop("ARROYO_TPU__CHECKPOINT__STORAGE_URL", None)
        faults.clear()
        cfg.update({"controller.workers-per-job": 1,
                    "checkpoint.interval-ms": 10_000})
        ctl.stop()
        api.stop()


# ------------------------------------------- health, end to end


def test_sustained_breach_degrades_job_visibly(tmp_path, _storage, capsys):
    """Acceptance: a job whose watermark lag sustainedly breaches its
    (deliberately tiny) ceiling transitions ok -> degraded within the
    configured fire-ticks — visible in the jobs API `health` field, the
    per-rule /health endpoint, the HEALTH_DEGRADED event, the
    arroyo_job_health gauge, and the `top` header line."""
    from arroyo_tpu import cli
    from arroyo_tpu import config as cfg
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.metrics import registry

    sql, out = _sql(tmp_path)
    db_path = str(tmp_path / "ctl.db")
    db = Database(db_path)
    cfg.update({
        "checkpoint.interval-ms": 10_000,
        "testing.source-read-delay-micros": 15000,
        # input timestamps are micros-from-zero, so observed lag is ~the
        # wall clock: any positive ceiling is a sustained breach
        "health.watermark-lag-max-s": 0.001,
        "health.fire-ticks": 2,
    })
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    api = ApiServer(db, port=0).start()
    try:
        # parallelism 1: the single-reader source feeds every subtask, so
        # the sink observes watermarks (and therefore lag) mid-run
        pid = db.create_pipeline("agg", sql, 1)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            job = db.get_job(jid)
            if (job.get("health") == "degraded"
                    or job["state"] != "Running"):
                break
            time.sleep(0.05)
        assert db.get_job(jid)["health"] == "degraded"

        # per-rule detail names the breaching rule with observed/threshold
        base = f"http://127.0.0.1:{api.port}"
        with urllib.request.urlopen(f"{base}/api/v1/jobs/{jid}/health",
                                    timeout=10) as r:
            detail = json.loads(r.read())
        assert detail["state"] == "degraded"
        lag = next(r for r in detail["rules"] if r["rule"] == "watermark-lag")
        assert lag["firing"] and lag["value"] > lag["threshold"]

        # the transition emitted exactly one HEALTH_DEGRADED event
        degraded = [e for e in db.list_events(jid)
                    if e["code"] == "HEALTH_DEGRADED"]
        assert len(degraded) == 1 and degraded[0]["level"] == "WARN"
        assert any(f["rule"] == "watermark-lag"
                   for f in degraded[0]["data"]["firing"])

        # exposition gauge + the top header line
        text = registry.prometheus_text()
        assert (f'arroyo_job_health{{job="{jid}",state="degraded"}} 1'
                in text), text
        assert cli.main(["top", jid, "--db", db_path, "--once"]) == 0
        assert "health=degraded" in capsys.readouterr().out

        ctl.wait_for_state(jid, "Finished", timeout=120)
    finally:
        cfg.update({"checkpoint.interval-ms": 10_000,
                    "testing.source-read-delay-micros": 0,
                    "health.watermark-lag-max-s": 900.0,
                    "health.fire-ticks": 3})
        ctl.stop()
        api.stop()
