"""Sliding (hop) window aggregate: overlap semantics, watermark-driven
emission, device vs numpy backends, checkpoint/restore."""

import numpy as np
import pytest

from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
from arroyo_tpu.engine import Engine, run_graph
from arroyo_tpu.expr import BinOp, Col, Lit
from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

DUMMY = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])


def sliding_graph(rows, backend, count=1000, width=1_000_000, slide=250_000,
                  parallelism=1, agg_parallelism=1):
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "impulse", "message_count": count,
        "interval_micros": 1000, "start_time_micros": 0}, parallelism))
    g.add_node(Node("wm", OpName.WATERMARK, {"expr": Col(TIMESTAMP_FIELD)}, parallelism))
    g.add_node(Node("key", OpName.KEY,
                    {"keys": [("k", BinOp("%", Col("counter"), Lit(5)))]}, parallelism))
    g.add_node(Node("agg", OpName.SLIDING_AGGREGATE, {
        "width_micros": width,
        "slide_micros": slide,
        "key_fields": ["k"],
        "aggregates": [("cnt", "count", None), ("total", "sum", Col("counter"))],
        "input_dtype_of": lambda e: np.dtype(np.int64),
        "backend": backend,
    }, agg_parallelism))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "wm", EdgeType.FORWARD, DUMMY)
    g.add_edge("wm", "key", EdgeType.FORWARD, DUMMY)
    g.add_edge("key", "agg", EdgeType.SHUFFLE, DUMMY)
    g.add_edge("agg", "sink", EdgeType.SHUFFLE, DUMMY)
    return g


def expected_sliding(count=1000, width=1_000_000, slide=250_000, interval=1000,
                     scale=1):
    """counter c: ts=c*interval, key=c%5. Window starting at s covers
    [s, s+width). Windows emitted for any start s=j*slide with data."""
    out = {}
    for c in range(count):
        ts = c * interval
        k = c % 5
        # windows containing ts: starts s with s <= ts < s + width, s = j*slide
        j_hi = ts // slide
        j_lo = (ts - width) // slide + 1
        for j in range(j_lo, j_hi + 1):
            s = j * slide
            cnt, tot = out.get((s, k), (0, 0))
            out[(s, k)] = (cnt + scale, tot + c * scale)
    return out


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_sliding_count_sum(backend):
    rows: list = []
    g = sliding_graph(rows, backend)
    run_graph(g, job_id=f"sw-{backend}", timeout=120)
    got = {(r["window_start"], r["k"]): (r["cnt"], r["total"]) for r in rows}
    exp = expected_sliding()
    assert got == exp
    for r in rows:
        assert r["window_end"] - r["window_start"] == 1_000_000


def test_sliding_parallel():
    rows: list = []
    g = sliding_graph(rows, "numpy", count=2000, parallelism=2, agg_parallelism=2)
    run_graph(g, job_id="swp", timeout=120)
    got = {(r["window_start"], r["k"]): (r["cnt"], r["total"]) for r in rows}
    # two identical sources double every count/sum
    exp = {}
    for (s, k), (c, t) in expected_sliding(2000).items():
        exp[(s, k)] = (c * 2, t * 2)
    assert got == exp


def test_sliding_incremental_emission():
    """Windows close as the watermark passes, across many small batches."""
    from arroyo_tpu.config import update

    update({"pipeline.source-batch-size": 100})
    rows: list = []
    g = sliding_graph(rows, "numpy", count=3000, width=400_000, slide=100_000)
    run_graph(g, job_id="sw-incr", timeout=120)
    got = {(r["window_start"], r["k"]): (r["cnt"], r["total"]) for r in rows}
    assert got == expected_sliding(3000, width=400_000, slide=100_000)


def test_width_must_be_multiple_of_slide():
    from arroyo_tpu.windows.sliding import SlidingAggregate

    with pytest.raises(ValueError):
        SlidingAggregate({
            "width_micros": 1_000_000, "slide_micros": 300_000,
            "aggregates": [("cnt", "count", None)],
        })


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_sliding_checkpoint_restore(backend):
    rows1: list = []
    count, width, slide = 2000, 500_000, 125_000
    g1 = sliding_graph(rows1, backend, count=count, width=width, slide=slide)
    run_graph(g1, job_id=f"sref-{backend}", timeout=120)
    expected = {(r["window_start"], r["k"]): (r["cnt"], r["total"]) for r in rows1}

    rows2: list = []
    g2 = sliding_graph(rows2, backend, count=count, width=width, slide=slide)
    g2.nodes["src"].config["event_rate"] = 2000
    eng = Engine(g2, job_id=f"sckpt-{backend}")
    eng.start()
    assert eng.checkpoint_and_wait(1, timeout=30)
    eng.stop()
    eng.join(timeout=30)

    rows3: list = []
    g3 = sliding_graph(rows3, backend, count=count, width=width, slide=slide)
    eng3 = Engine(g3, job_id=f"sckpt-{backend}", restore_epoch=1)
    eng3.run_to_completion(timeout=120)
    merged = {}
    for r in rows2 + rows3:
        merged[(r["window_start"], r["k"])] = (r["cnt"], r["total"])
    assert merged == expected


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_sliding_mixed_key_transport_restore(backend):
    """Mixed group-by keys: the numeric column rides aggregate-store lanes,
    the string column rides the host KeyDictionary (r5 split) — both must
    survive checkpoint/restore with exact per-window results."""
    from arroyo_tpu.expr import BinOp, Case, Col, Lit

    def graph(rows, event_rate=None):
        g = Graph()
        cfg = {"connector": "impulse", "message_count": 1500,
               "interval_micros": 1000, "start_time_micros": 0}
        if event_rate:
            cfg["event_rate"] = event_rate
        g.add_node(Node("src", OpName.SOURCE, cfg, 1))
        g.add_node(Node("wm", OpName.WATERMARK, {"expr": Col(TIMESTAMP_FIELD)}, 1))
        # key: (counter % 3 as int lane, parity name as dict string)
        parity = Case(((BinOp("==", BinOp("%", Col("counter"), Lit(2)), Lit(0)),
                        Lit("even")),), Lit("odd"))
        g.add_node(Node("key", OpName.KEY, {"keys": [
            ("k", BinOp("%", Col("counter"), Lit(3))), ("p", parity)]}, 1))
        g.add_node(Node("agg", OpName.SLIDING_AGGREGATE, {
            "width_micros": 500_000, "slide_micros": 125_000,
            "key_fields": ["k", "p"],
            "aggregates": [("cnt", "count", None), ("total", "sum", Col("counter"))],
            "input_dtype_of": lambda e: np.dtype(np.int64),
            "backend": backend,
        }, 1))
        g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
        g.add_edge("src", "wm", EdgeType.FORWARD, DUMMY)
        g.add_edge("wm", "key", EdgeType.FORWARD, DUMMY)
        g.add_edge("key", "agg", EdgeType.SHUFFLE, DUMMY)
        g.add_edge("agg", "sink", EdgeType.FORWARD, DUMMY)
        return g

    rows1: list = []
    run_graph(graph(rows1), job_id=f"smix-{backend}", timeout=120)
    expected = {(r["window_start"], r["k"], r["p"]): (r["cnt"], r["total"])
                for r in rows1}
    assert expected, "reference run emitted nothing"
    assert {r["p"] for r in rows1} == {"even", "odd"}

    rows2: list = []
    eng = Engine(graph(rows2, event_rate=2000), job_id=f"smix-ck-{backend}")
    eng.start()
    assert eng.checkpoint_and_wait(1, timeout=30)
    eng.stop()
    eng.join(timeout=30)
    rows3: list = []
    eng3 = Engine(graph(rows3), job_id=f"smix-ck-{backend}", restore_epoch=1)
    eng3.run_to_completion(timeout=120)
    merged = {}
    for r in rows2 + rows3:
        merged[(r["window_start"], r["k"], r["p"])] = (r["cnt"], r["total"])
    assert merged == expected
