"""Test env: force JAX onto a virtual 8-device CPU platform before any jax
import, so sharding/collective tests run without TPU hardware (the driver
separately dry-runs the multi-chip path; bench.py runs on the real chip)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's TPU-tunnel shim (sitecustomize) force-sets
# jax.config jax_platforms at interpreter startup, which overrides the env
# var — override it back BEFORE any backend initializes, or every test
# process contends for the single TPU tunnel and deadlocks.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _storage(tmp_path, monkeypatch):
    """Point checkpoint storage at a fresh tmp dir for every test."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.state import storage as _st

    cfg.reset()
    cfg.update({
        "checkpoint.storage-url": str(tmp_path / "checkpoints"),
        # small device tables keep CPU-mode jit compile/exec fast in tests
        "device.table-capacity": 8192,
        "device.batch-capacity": 1024,
        "device.emit-capacity": 1024,
        "device.max-probes": 32,
        # chaos runs use sub-second retry delays; production default is 50ms
        "storage.retry.base-delay-ms": 10,
    })
    yield str(tmp_path / "checkpoints")
    # fault plans and storage circuit state never leak across tests
    faults.clear()
    _st.reset_retry_state()
    cfg.reset()


@pytest.fixture(scope="session", autouse=True)
def _operators():
    import arroyo_tpu

    arroyo_tpu._load_operators()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "no_native_required: runs even when the native library is unavailable")
    config.addinivalue_line(
        "markers", "chaos: fault-injection suite (runs pipelines under induced "
                   "failures and asserts byte-exact recovery)")
    config.addinivalue_line(
        "markers", "slow: long soak tests excluded from the tier-1 budget")
    config.addinivalue_line(
        "markers", "mesh: multi-device mesh execution suite (8 emulated "
                   "devices; tools/lint.sh --mesh-tests runs just these)")


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """On any failure while a fault plan is active, print the plan + seed
    (and which faults fired) so the chaos run can be replayed exactly."""
    outcome = yield
    rep = outcome.get_result()
    if rep.when == "call" and rep.failed:
        try:
            from arroyo_tpu import faults

            inj = faults.active()
            if inj is not None:
                fired = "\n".join(inj.fired_log[-20:]) or "(no faults fired)"
                rep.sections.append((
                    "fault injection",
                    f"plan={inj.plan!r} seed={inj.seed}\nfired:\n{fired}",
                ))
        except Exception:
            pass
