"""Test env: force JAX onto a virtual 8-device CPU platform before any jax
import, so sharding/collective tests run without TPU hardware (the driver
separately dry-runs the multi-chip path; bench.py runs on the real chip)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's TPU-tunnel shim (sitecustomize) force-sets
# jax.config jax_platforms at interpreter startup, which overrides the env
# var — override it back BEFORE any backend initializes, or every test
# process contends for the single TPU tunnel and deadlocks.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _storage(tmp_path, monkeypatch):
    """Point checkpoint storage at a fresh tmp dir for every test."""
    from arroyo_tpu import config as cfg

    cfg.reset()
    cfg.update({
        "checkpoint.storage-url": str(tmp_path / "checkpoints"),
        # small device tables keep CPU-mode jit compile/exec fast in tests
        "device.table-capacity": 8192,
        "device.batch-capacity": 1024,
        "device.emit-capacity": 1024,
        "device.max-probes": 32,
    })
    yield str(tmp_path / "checkpoints")
    cfg.reset()


@pytest.fixture(scope="session", autouse=True)
def _operators():
    import arroyo_tpu

    arroyo_tpu._load_operators()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "no_native_required: runs even when the native library is unavailable")
