"""Elastic autoscaler: the control loop's rails, and the chaos axis.

Unit tests drive ``controller.autoscaler.Autoscaler`` with a FAKE clock
and hand-fed metrics snapshots — no wall-time sleeps, because this box's
CPU throttling makes real-time hysteresis assertions flaky. Only the
end-to-end chaos tests (scale-up mid-stream, scale-down with state
repartitioning, worker crash during the scale transition, controller
restart mid-rescale) touch real time, and they assert byte-exact golden
output plus the AUTOSCALE_* event trail rather than durations.
"""

from __future__ import annotations

import glob
import json
import os
import time

import pytest

from arroyo_tpu.controller import ControllerServer, Database
from arroyo_tpu.controller.autoscaler import Autoscaler
from arroyo_tpu.controller.scheduler import EmbeddedScheduler

SMOKE = os.path.join(os.path.dirname(__file__), "smoke")

HOT = {"agg": {"backpressure": 0.95, "busy_pct": 90.0}}
IDLE = {"agg": {"backpressure": 0.0, "busy_pct": 5.0}}


def _sql(tmp_path, name="grouped_aggregates"):
    with open(os.path.join(SMOKE, "queries", f"{name}.sql")) as f:
        sql = f.read()
    out = str(tmp_path / "out.json")
    return sql.replace("$input_dir", os.path.join(SMOKE, "inputs")).replace(
        "$output_path", out
    ), out


def _assert_golden(out, name="grouped_aggregates"):
    got = []
    for p in sorted(glob.glob(out) + glob.glob(out + ".*")):
        with open(p) as f:
            got.extend(json.loads(l) for l in f if l.strip())
    with open(os.path.join(SMOKE, "golden", f"{name}.json")) as f:
        want = [json.loads(l) for l in f if l.strip()]
    key = lambda r: json.dumps(r, sort_keys=True)
    assert sorted(map(key, got)) == sorted(map(key, want))


def _loop(**over):
    """A fake-clock Autoscaler plus its captured events and the clock."""
    from arroyo_tpu import config as cfg

    cfg.update({"autoscaler.enabled": True, "autoscaler.up-ticks": 3,
                "autoscaler.down-ticks": 4, "autoscaler.cooldown-s": 30.0,
                "autoscaler.backoff-base-s": 10.0,
                "autoscaler.max-parallelism": 4,
                **{f"autoscaler.{k.replace('_', '-')}": v
                   for k, v in over.items()}})
    clock = [1000.0]
    events: list[tuple] = []
    a = Autoscaler(
        "j1", emit=lambda lvl, code, msg, **kw: events.append(
            (lvl, code, kw.get("data"))),
        clock=lambda: clock[0])
    return a, events, clock


# ------------------------------------------------------ unit: the rails


def test_scale_up_hysteresis_and_cooldown():
    a, events, clock = _loop()
    a.on_worker_set_started()  # fresh set arms the cooldown
    # sustained pressure during cooldown decides nothing...
    for _ in range(6):
        assert a.evaluate(HOT, running=True, parallelism=2) is None
    # ...but the armed streak fires on the first post-cooldown tick
    clock[0] += 31
    assert a.evaluate(HOT, running=True, parallelism=2) == 4
    assert a.in_flight == 4
    assert [c for _l, c, _d in events] == ["AUTOSCALE_DECISION"]
    assert events[0][2]["signals"] == ["backpressure"]
    # in-flight gates further decisions until the set restarts
    assert a.evaluate(HOT, running=True, parallelism=2) is None
    a.on_worker_set_started()
    assert a.in_flight is None
    # one breached tick is not hysteresis
    clock[0] += 31
    assert a.evaluate(HOT, running=True, parallelism=4) is None
    assert a.evaluate(IDLE, running=True, parallelism=4) is None  # resets
    for _ in range(2):
        assert a.evaluate(HOT, running=True, parallelism=4) is None


def test_scale_down_only_on_proven_headroom():
    a, events, clock = _loop(down_ticks=3, cooldown_s=0.0)
    # absent busy%/backpressure proves nothing: no scale-down, ever
    for _ in range(10):
        assert a.evaluate({"agg": {"backpressure": 0.0}},
                          running=True, parallelism=4) is None
    # empty snapshot proves nothing either
    for _ in range(10):
        assert a.evaluate(None, running=True, parallelism=4) is None
    # proven headroom: three consecutive ticks, then down 4 -> 2
    assert a.evaluate(IDLE, running=True, parallelism=4) is None
    assert a.evaluate(IDLE, running=True, parallelism=4) is None
    assert a.evaluate(IDLE, running=True, parallelism=4) == 2
    d = events[-1][2]
    assert d["direction"] == "down" and d["from"] == 4 and d["to"] == 2
    a.on_worker_set_started()
    # a pressured tick resets the headroom streak
    assert a.evaluate(IDLE, running=True, parallelism=2) is None
    assert a.evaluate(IDLE, running=True, parallelism=2) is None
    assert a.evaluate(HOT, running=True, parallelism=2) is None
    assert a.evaluate(IDLE, running=True, parallelism=2) is None
    assert a.evaluate(IDLE, running=True, parallelism=2) is None
    # min-parallelism floor: at p=1 a headroom streak decides a no-op,
    # emits the decision ONCE, and never churns the set
    n_events = len(events)
    for _ in range(9):
        assert a.evaluate(IDLE, running=True, parallelism=1) is None
    noop = [e for e in events[n_events:] if e[1] == "AUTOSCALE_DECISION"]
    assert len(noop) == 1 and noop[0][2]["to"] == 1


def test_never_scales_while_not_running_or_mid_ckpt_failures():
    a, _events, _clock = _loop(up_ticks=2, cooldown_s=0.0)
    for _ in range(5):
        assert a.evaluate(HOT, running=False, parallelism=2) is None
    # the counters reset while gated: coming back Running starts over
    assert a.evaluate(HOT, running=True, parallelism=2) is None
    # a checkpoint-failure streak gates (and resets) too: the drain
    # checkpoint a rescale needs is exactly what's wedging
    assert a.evaluate(HOT, running=True, parallelism=2,
                      ckpt_failures=1) is None
    assert a.evaluate(HOT, running=True, parallelism=2) is None
    assert a.evaluate(HOT, running=True, parallelism=2) == 4


def test_backoff_is_exponential_and_resets_on_clean_scale():
    a, events, clock = _loop(up_ticks=1, cooldown_s=0.0)
    # attempt 1 disrupted -> 10s window; attempt 2 -> 20s; attempt 3 -> 40s
    for expected in (10.0, 20.0, 40.0):
        t = a.evaluate(HOT, running=True, parallelism=2)
        assert t == 4
        a.on_scale_disrupted("worker died mid-drain")
        backoffs = [d for _l, c, d in events if c == "AUTOSCALE_BACKOFF"]
        assert backoffs[-1]["backoff_s"] == expected
        a.on_worker_set_started()  # transition still lands at the new scale
        # gated while the window is open, armed streak fires after
        assert a.evaluate(HOT, running=True, parallelism=2) is None
        clock[0] += expected + 1
    # a CLEAN completion resets the streak back to the base window
    assert a.evaluate(HOT, running=True, parallelism=2) == 4
    a.on_worker_set_started()
    a.evaluate(HOT, running=True, parallelism=2)
    a.on_scale_disrupted("again")
    backoffs = [d for _l, c, d in events if c == "AUTOSCALE_BACKOFF"]
    assert backoffs[-1]["backoff_s"] == 10.0


@pytest.mark.chaos
def test_rails_clamp_forced_bogus_target():
    """Chaos site autoscale_decide: a forced target far past the bounds
    must come out clamped; a forced 0 clamps to min-parallelism; drop
    suppresses the decision entirely."""
    from arroyo_tpu import faults

    a, events, _clock = _loop(up_ticks=1, cooldown_s=0.0,
                              min_parallelism=2, max_parallelism=4)
    faults.install("autoscale_decide:force=64@step=1", seed=3)
    try:
        assert a.evaluate(HOT, running=True, parallelism=3) == 4
        d = events[-1][2]
        assert d["raw_target"] == 64 and d["to"] == 4 and d["clamped"]
        a.on_worker_set_started()
        faults.install("autoscale_decide:force=0@step=1", seed=3)
        assert a.evaluate(HOT, running=True, parallelism=3) == 2
        d = events[-1][2]
        assert d["raw_target"] == 0 and d["to"] == 2 and d["clamped"]
        a.on_worker_set_started()
        faults.install("autoscale_decide:drop", seed=3)
        for _ in range(6):
            assert a.evaluate(HOT, running=True, parallelism=3) is None
        assert a.in_flight is None
        # a raising action costs one tick's decision, never the job
        faults.install("autoscale_decide:fail_once", seed=3)
        assert a.evaluate(HOT, running=True, parallelism=3) is None
        assert a.evaluate(HOT, running=True, parallelism=3) == 4
    finally:
        faults.clear()


def test_disabled_loop_decides_nothing():
    from arroyo_tpu import config as cfg

    a, events, _clock = _loop(up_ticks=1, cooldown_s=0.0)
    cfg.update({"autoscaler.enabled": False})
    for _ in range(5):
        assert a.evaluate(HOT, running=True, parallelism=1) is None
    assert not events


# --------------------------------------------- end to end, with goldens


def _controller(db, **cfg_over):
    from arroyo_tpu import config as cfg

    cfg.update(cfg_over)
    return ControllerServer(db, EmbeddedScheduler()).start()


BASE_CFG = {
    "checkpoint.interval-ms": 150,
    "testing.source-read-delay-micros": 4000,
    "autoscaler.enabled": True,
    "autoscaler.cooldown-s": 0.3,
}
RESET_CFG = {
    "checkpoint.interval-ms": 10_000,
    "checkpoint.timeout-ms": 600_000,
    "testing.source-read-delay-micros": 0,
    "autoscaler.enabled": False,
    "autoscaler.cooldown-s": 30.0,
    "autoscaler.up-ticks": 3,
    "autoscaler.down-ticks": 10,
    "autoscaler.up-watermark-lag-s": 30.0,
    "autoscaler.up-queue-transit-p99-ms": 750.0,
    "autoscaler.up-sink-latency-p99-s": 30.0,
    "autoscaler.down-busy-max-pct": 25.0,
    "autoscaler.down-backpressure-max": 0.1,
    "autoscaler.max-parallelism": 8,
}


@pytest.mark.chaos
def test_autoscale_up_midstream_golden(tmp_path, _storage):
    """A running job whose (deliberately hair-trigger) pressure signals
    breach scales itself 1 -> 2 -> 3 with NO rescale API call: decision,
    drain behind a final checkpoint, restore at the new parallelism —
    byte-exact goldens, the full AUTOSCALE event sequence, the target
    gauge, and the decision detail on the health record."""
    from arroyo_tpu.metrics import registry
    from arroyo_tpu.obs.events import trail

    sql, out = _sql(tmp_path)
    db = Database()
    # smoke input timestamps are historic, so watermark lag is always a
    # sustained breach: pressure without having to melt this CPU-capped box
    ctl = _controller(db, **BASE_CFG, **{
        "autoscaler.up-ticks": 2,
        "autoscaler.up-watermark-lag-s": 0.001,
        "autoscaler.max-parallelism": 3,
        "autoscaler.down-ticks": 10_000,
    })
    try:
        pid = db.create_pipeline("agg", sql, 1)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        seen = set()
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            state = db.get_job(jid)["state"]
            seen.add(state)
            if state in ("Finished", "Failed"):
                break
            time.sleep(0.02)
        from arroyo_tpu import config as cfg

        cfg.update({"testing.source-read-delay-micros": 0})
        assert ctl.wait_for_state(jid, "Finished", timeout=60) == "Finished"
        assert "Rescaling" in seen, seen
        # the pipeline rescaled itself to the configured max
        assert db.get_pipeline(pid)["parallelism"] == 3
        t = trail(db.list_events(jid))
        first = {c: t.index(c) for c in set(t)}
        assert first["AUTOSCALE_DECISION"] < first["AUTOSCALE_STARTED"] \
            < first["RESCALE"] < first["AUTOSCALE_DONE"], t
        # two scale-ups (1->2->3), each with its full sequence
        assert t.count("AUTOSCALE_DONE") == 2, t
        # the gauge tracked the target
        text = registry.prometheus_text()
        assert f'arroyo_autoscaler_target{{job="{jid}"}} 3' in text
        # /health carries the autoscaler readout incl. the last decision
        detail = (db.get_health(jid) or {}).get("autoscaler") or {}
        assert detail.get("enabled") and detail.get("parallelism") == 3
        assert (detail.get("last_decision") or {}).get("direction") == "up"
        _assert_golden(out)
    finally:
        from arroyo_tpu import config as cfg

        cfg.update(RESET_CFG)
        ctl.stop()


@pytest.mark.chaos
def test_autoscale_down_repartitions_state_golden(tmp_path, _storage):
    """Sustained headroom (every pressure ceiling effectively off, the
    headroom ceilings wide open) scales 3 -> 1: the keyed aggregate's
    state repartitions across the restore and output stays byte-exact."""
    from arroyo_tpu.obs.events import trail

    sql, out = _sql(tmp_path)
    db = Database()
    ctl = _controller(db, **BASE_CFG, **{
        "autoscaler.down-ticks": 3,
        "autoscaler.up-watermark-lag-s": 1e12,
        "autoscaler.up-queue-transit-p99-ms": 1e12,
        "autoscaler.up-sink-latency-p99-s": 1e12,
        "autoscaler.down-busy-max-pct": 100.0,
        "autoscaler.down-backpressure-max": 1.0,
    })
    try:
        pid = db.create_pipeline("agg", sql, 3)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        seen = set()
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            state = db.get_job(jid)["state"]
            seen.add(state)
            if state in ("Finished", "Failed"):
                break
            time.sleep(0.02)
        from arroyo_tpu import config as cfg

        cfg.update({"testing.source-read-delay-micros": 0})
        assert ctl.wait_for_state(jid, "Finished", timeout=60) == "Finished"
        assert "Rescaling" in seen, seen
        assert db.get_pipeline(pid)["parallelism"] == 1
        t = trail(db.list_events(jid))
        decisions = [e for e in db.list_events(jid)
                     if e["code"] == "AUTOSCALE_DECISION"]
        assert decisions[0]["data"]["direction"] == "down"
        assert decisions[0]["data"]["from"] == 3
        assert decisions[0]["data"]["to"] == 1
        assert "AUTOSCALE_DONE" in t
        _assert_golden(out)
    finally:
        from arroyo_tpu import config as cfg

        cfg.update(RESET_CFG)
        ctl.stop()


@pytest.mark.chaos
def test_worker_crash_during_scale_transition_golden(tmp_path, _storage):
    """The worker crashes AT the drain barrier of an autoscaler-initiated
    rescale (periodic checkpoints disabled, so the scale transition's
    stopping epoch is the only barrier): the transition is disrupted, the
    autoscaler arms its backoff, the controller still proceeds to the new
    parallelism from whatever checkpoint exists — and output stays
    byte-exact because nothing ever went durable."""
    from arroyo_tpu import faults
    from arroyo_tpu.obs.events import trail

    sql, out = _sql(tmp_path)
    db = Database()
    faults.install("worker:crash@step=1", seed=7)
    ctl = _controller(db, **{**BASE_CFG,
        "checkpoint.interval-ms": 600_000,  # the drain is the only barrier
        "autoscaler.up-ticks": 2,
        "autoscaler.up-watermark-lag-s": 0.001,
        "autoscaler.max-parallelism": 2,
    })
    try:
        pid = db.create_pipeline("agg", sql, 1)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if db.get_job(jid)["state"] in ("Finished", "Failed"):
                break
            time.sleep(0.02)
        from arroyo_tpu import config as cfg

        cfg.update({"testing.source-read-delay-micros": 0})
        assert ctl.wait_for_state(jid, "Finished", timeout=90) == "Finished"
        assert faults.active().fired_log, "barrier crash never fired"
        t = trail(db.list_events(jid))
        assert "AUTOSCALE_STARTED" in t and "WORKER_LOST" in t, t
        assert "AUTOSCALE_BACKOFF" in t, t
        # disrupted or not, the scale landed
        assert t.index("WORKER_LOST") < t.index("AUTOSCALE_DONE"), t
        assert db.get_pipeline(pid)["parallelism"] == 2
        assert int(db.get_job(jid)["restarts"]) >= 1
        _assert_golden(out)
    finally:
        faults.clear()
        from arroyo_tpu import config as cfg

        cfg.update(RESET_CFG)
        ctl.stop()


@pytest.mark.chaos
def test_rescale_command_dropped_watchdog_retries_golden(tmp_path, _storage):
    """Chaos site `rescale`: the drain trigger of a live rescale is lost
    mid-transition. The stuck-epoch watchdog must declare the drain epoch
    failed and re-trigger it (then_stop intact) — the job reaches the new
    parallelism with byte-exact output instead of wedging in Rescaling."""
    from arroyo_tpu import faults
    from arroyo_tpu.obs.events import trail

    sql, out = _sql(tmp_path)
    db = Database()
    inj = faults.install("rescale:drop@step=1", seed=11)
    ctl = _controller(db, **{
        "checkpoint.interval-ms": 10_000,  # no periodic epochs in the way
        "checkpoint.timeout-ms": 400,
        "testing.source-read-delay-micros": 6000,
    })
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        time.sleep(0.3)
        db.update_job(jid, desired_parallelism=3)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(c["state"] == "failed" for c in db.list_checkpoints(jid)):
                break
            time.sleep(0.02)
        assert any(c["state"] == "failed" for c in db.list_checkpoints(jid)), \
            "dropped drain trigger was never declared wedged"
        assert inj.fired_log, "rescale drop never fired"
        from arroyo_tpu import config as cfg

        cfg.update({"testing.source-read-delay-micros": 0})
        assert ctl.wait_for_state(jid, "Finished", timeout=90) == "Finished"
        assert db.get_pipeline(pid)["parallelism"] == 3
        assert "EPOCH_WEDGED" in trail(db.list_events(jid))
        _assert_golden(out)
    finally:
        faults.clear()
        from arroyo_tpu import config as cfg

        cfg.update(RESET_CFG)
        ctl.stop()


def _run_restart_mid_rescale(tmp_path, clear_desired: bool):
    """Shared driver: wedge a live rescale mid-drain (dropped trigger, no
    watchdog), kill the controller, optionally erase desired_parallelism,
    and let a FRESH controller adopt the Rescaling job."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults

    sql, out = _sql(tmp_path)
    db = Database(str(tmp_path / "ctl.db"))
    # drop the drain trigger and leave the watchdog off: the job parks in
    # Rescaling deterministically until the controller dies
    faults.install("rescale:drop@step=1", seed=13)
    ctl = _controller(db, **{
        "checkpoint.interval-ms": 10_000,
        "checkpoint.timeout-ms": 600_000,
        "testing.source-read-delay-micros": 10_000,
    })
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        time.sleep(0.3)
        db.update_job(jid, desired_parallelism=3)
        ctl.wait_for_state(jid, "Rescaling", timeout=60)
    finally:
        ctl.stop()  # kills the draining worker set; job row stays Rescaling
    faults.clear()
    assert db.get_job(jid)["state"] == "Rescaling"
    if clear_desired:
        db.update_job(jid, desired_parallelism=None)
    cfg.update({"testing.source-read-delay-micros": 0})
    ctl2 = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        assert ctl2.wait_for_state(jid, "Finished", timeout=120) == "Finished"
        _assert_golden(out)
        return db, pid, jid
    finally:
        cfg.update(RESET_CFG)
        ctl2.stop()


@pytest.mark.chaos
def test_controller_restart_mid_rescale_adopts_target(tmp_path, _storage):
    """A fresh controller adopting a Rescaling job (no worker handles)
    must finish the rescale from the persisted desired_parallelism — the
    controller.py:224 adoption path — and produce byte-exact output."""
    db, pid, _jid = _run_restart_mid_rescale(tmp_path, clear_desired=False)
    assert db.get_pipeline(pid)["parallelism"] == 3


@pytest.mark.chaos
def test_controller_restart_mid_rescale_desired_unset(tmp_path, _storage):
    """Adoption with desired_parallelism ALREADY cleared in the DB row:
    the `_finish_rescale` fallback to self.rescale_to is None on a fresh
    controller, and the job must degrade to the old parallelism — not
    crash, not wedge in Rescaling."""
    db, pid, jid = _run_restart_mid_rescale(tmp_path, clear_desired=True)
    assert db.get_pipeline(pid)["parallelism"] == 2
    assert db.get_job(jid)["desired_parallelism"] is None


def test_actuation_write_never_clobbers_manual_request(_storage):
    """The autoscaler actuates via a compare-and-set: its write lands only
    while no rescale request is pending, so a manual PATCH racing the
    supervision tick keeps its value (manual requests always win)."""
    db = Database()
    pid = db.create_pipeline("p", "CREATE TABLE x (a BIGINT)", 1)
    jid = db.create_job(pid)
    assert db.set_desired_parallelism_if_unset(jid, 2) is True
    # a pending request (here: the one just written) blocks later writes
    assert db.set_desired_parallelism_if_unset(jid, 4) is False
    assert db.get_job(jid)["desired_parallelism"] == 2
    db.clear_desired_parallelism(jid, 2)
    assert db.set_desired_parallelism_if_unset(jid, 3) is True
    assert db.get_job(jid)["desired_parallelism"] == 3


def test_noop_at_bound_dedups_across_fluctuating_signals():
    """A job pinned at a bound under sustained overload must emit its
    no-op decision once per (direction, from, to) — a fluctuating set of
    breaching signals between hysteresis windows must not re-emit it."""
    hot_a = {"agg": {"backpressure": 0.95}}
    hot_b = {"agg": {"backpressure": 0.95,
                     "watermark_lag_seconds": 1e6}}
    a, events, _clock = _loop(up_ticks=1, cooldown_s=0.0, max_parallelism=2)
    for snap in (hot_a, hot_b, hot_a, hot_b):
        assert a.evaluate(snap, running=True, parallelism=2) is None
    noop = [e for e in events if e[1] == "AUTOSCALE_DECISION"]
    assert len(noop) == 1
