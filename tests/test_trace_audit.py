"""Trace-safety auditor (analysis/trace_audit.py): LR301–LR305 + AR009.

Four layers:
- per-rule positive and negative AST fixtures, waiver grammar, and the
  alias-dodge fixtures for the hardened LR104/LR109/LR111 resolution;
- the repo-audit-clean gate plus regression locks for every real finding
  the sweep fixed (the ``to_timestamp_micros`` allowlist gap, the
  floor/ceil/sqrt integer dtype divergence, the unpinned-x64 trace entry);
- the runtime PARITY ORACLE: every allowlisted func/binop evaluated
  interpreted (numpy) and freshly jitted, compared ``tobytes``-exactly
  across the dtype matrix including NaN, ±0.0, int extremes, and empty
  arrays — the bit-exactness claim behind ``_TRACEABLE_FUNCS`` is
  measured, not asserted;
- AR009: the dual-path dtype model pinned against real jitted dtypes,
  plan-time rejection of divergent pipelines, and the ``not compilable``
  surfacing in check/executed_graph_view/explain/top.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import arroyo_tpu
from arroyo_tpu.analysis import (
    Severity,
    audit_trace_source,
    audit_trace_sources,
    check_sql,
    lint_paths,
    lint_source,
    render_json,
    render_sarif,
)

PKG_DIR = os.path.dirname(os.path.abspath(arroyo_tpu.__file__))

# every fixture module pins x64 (imports arroyo_tpu.ops) unless the test
# is specifically about the missing pin
_PINNED = "from arroyo_tpu.ops import require_x64\n"


def ids_of(diags):
    return {d.rule_id for d in diags}


def lines_of(diags, rule):
    return sorted(int(d.site.rsplit(":", 1)[1]) for d in diags
                  if d.rule_id == rule)


# ============================================================ LR301 purity


LR301_FIXTURE = _PINNED + '''
import jax
import jax.numpy as jnp
import numpy as np

def build():
    def fn(x, n):
        if x > 0:                       # if on traced
            pass
        v = float(x)                    # float() on traced
        w = np.asarray(x)               # numpy on traced
        x.item()                        # host sync
        return v, w
    return jax.jit(fn)
'''


def test_lr301_positive():
    diags = audit_trace_source(LR301_FIXTURE, "engine/fixture.py")
    assert ids_of(diags) == {"LR301"}
    assert len([d for d in diags if d.rule_id == "LR301"]) == 4
    assert all(d.severity == Severity.ERROR for d in diags)


def test_lr301_negative_static_metadata_and_identity():
    """Branching on static config/metadata and ``is None`` identity is
    ordinary trace-time specialization, not impurity."""
    src = _PINNED + '''
import jax
import jax.numpy as jnp
import numpy as np

def build(plan):
    def fn(x, n):
        if plan.debug:                  # static config
            y = jnp.abs(x)
        else:
            y = x
        if x is None:                   # trace-time identity
            return None
        if x.dtype.kind == "f":         # static metadata
            y = y + 1
        f = np.dtype(x.dtype)           # numpy metadata call
        base = jnp.arange(n, dtype=jnp.int64) < n
        return jnp.where(base, y, 0)
    return jax.jit(fn)
'''
    assert audit_trace_source(src, "engine/fixture.py") == []


def test_lr301_self_state():
    src = _PINNED + '''
import jax
import jax.numpy as jnp

class Op:
    def __init__(self):
        self.cache = None
        self.high = 0
    def bump(self, v):
        self.high = v                  # mutable outside __init__
    def eval_jnp(self, cols):
        self.cache = cols["a"]         # write under trace
        return jnp.abs(cols["a"]) + self.high   # read of mutable state
'''
    diags = audit_trace_source(src, "engine/fixture.py")
    msgs = [d.message for d in diags if d.rule_id == "LR301"]
    assert any("write to self.cache" in m for m in msgs)
    assert any("mutable member state self.high" in m for m in msgs)


def test_lr301_frozen_reads_are_clean():
    """Reads of attributes never mutated outside __init__ (the frozen
    Expr dataclass shape) are trace-time constants, not findings."""
    src = _PINNED + '''
import jax
import jax.numpy as jnp

class Expr:
    def __init__(self, name):
        self.name = name
    def eval_jnp(self, cols):
        return jnp.abs(cols[self.name])
'''
    assert audit_trace_source(src, "engine/fixture.py") == []


def test_lr301_taint_through_closure_helpers():
    """A helper in the trace closure whose return derives from jnp taints
    its callers; a metadata-only helper does not."""
    src = _PINNED + '''
import jax
import jax.numpy as jnp

def _lift(v):
    return jnp.asarray(v)

def _is_float(v):
    return v.dtype.kind == "f"

def build():
    def fn(x):
        y = _lift(x)
        if _is_float(x):               # host bool from metadata: clean
            y = y + 1
        v = float(y)                   # y is traced through _lift
        return v
    return jax.jit(fn)
'''
    diags = audit_trace_source(src, "engine/fixture.py")
    assert [d.rule_id for d in diags] == ["LR301"]
    assert "float()" in diags[0].message


# ====================================================== LR302 shape stable


def test_lr302_positive_and_negative():
    src = _PINNED + '''
import jax
import jax.numpy as jnp

def build():
    def fn(x):
        a = jnp.nonzero(x)             # no size=
        b = jnp.where(x > 0)           # single-arg where
        c = x[x > 0]                   # boolean mask
        d = jnp.nonzero(x, size=8)     # pinned: fine
        e = jnp.where(x > 0, x, 0)     # three-arg: fine
        idx = jnp.argsort(x)
        f = x[idx]                     # integer gather: shape-stable
        return a, b, c, d, e, f
    return jax.jit(fn)
'''
    diags = audit_trace_source(src, "engine/fixture.py")
    assert ids_of(diags) == {"LR302"}
    assert len(diags) == 3


# ==================================================== LR303 allowlist drift


SEG_FIXTURE = '''
_TRACEABLE_FUNCS = {"abs", "ghost_fn"}
_TRACEABLE_BINOPS = {"+"}
_KNOWN_DIVERGENT_FUNCS = {"exp"}
'''

EXPR_FIXTURE = '''
_NP_BINOPS = {"+": None, "*": None}
class Func:
    def eval_np(self, cols, n):
        name = self.name
        if name == "abs": return None
        if name == "exp": return None
        if name == "sqrt": return None
    def eval_jnp(self, cols):
        name = self.name
        table = {"abs": None, "exp": None, "sqrt": None}
        if name in table: return None
class BinOp:
    def eval_jnp(self, cols):
        return {"+": None, "*": None}[self.op]
'''


def test_lr303_drift_both_directions():
    diags = audit_trace_sources([
        (SEG_FIXTURE, "arroyo_tpu/engine/segment.py"),
        (EXPR_FIXTURE, "arroyo_tpu/expr.py"),
    ])
    errs = [d for d in diags if d.severity == Severity.ERROR]
    warns = [d for d in diags if d.severity == Severity.WARNING]
    # ghost_fn is allowlisted with neither twin: two errors (np + jnp)
    assert sum("ghost_fn" in d.message for d in errs) == 2
    # sqrt implemented both ways but unlisted and not known-divergent
    assert any("'sqrt'" in d.message for d in warns)
    # '*' implemented both ways but unlisted
    assert any("'*'" in d.message for d in warns)
    # exp is declared divergent: silent, not a finding
    assert not any("'exp'" in d.message for d in diags)


def test_lr303_contradiction():
    seg = SEG_FIXTURE.replace('{"exp"}', '{"exp", "abs"}')
    diags = audit_trace_sources([
        (seg, "arroyo_tpu/engine/segment.py"),
        (EXPR_FIXTURE, "arroyo_tpu/expr.py"),
    ])
    assert any("both _TRACEABLE_FUNCS and" in d.message
               and d.severity == Severity.ERROR for d in diags)


def test_lr303_regression_to_timestamp_micros():
    """The real finding this PR's sweep caught: to_timestamp_micros was
    allowlisted in _TRACEABLE_FUNCS with no eval_jnp builder — every
    segment using it compiled, raised NotImplementedError at trace time,
    and silently fell back. The fixture reproduces the pre-fix shape; the
    repo-clean gate proves the live pair stays consistent."""
    seg = SEG_FIXTURE.replace('"ghost_fn"', '"to_timestamp_micros"')
    diags = audit_trace_sources([
        (seg, "arroyo_tpu/engine/segment.py"),
        (EXPR_FIXTURE, "arroyo_tpu/expr.py"),
    ])
    assert any("to_timestamp_micros" in d.message and "no jnp trace builder"
               in d.message for d in diags)


# ========================================================== LR304 dtypes


def test_lr304_ctor_and_astype():
    src = _PINNED + '''
import jax
import jax.numpy as jnp

def build():
    def fn(x, n):
        a = jnp.arange(n)              # default dtype follows x64 flag
        b = jnp.zeros(4)               # same
        c = x.astype(int)              # Python builtin width
        d = jnp.arange(n, dtype=jnp.int64)   # fine
        e = jnp.zeros(4, jnp.float64)        # positional dtype: fine
        f = x.astype(jnp.int64)              # fine
        return a, b, c, d, e, f
    return jax.jit(fn)
'''
    diags = audit_trace_source(src, "engine/fixture.py")
    assert ids_of(diags) == {"LR304"}
    assert len(diags) == 3


def test_lr304_missing_x64_pin():
    src = '''
import jax
import jax.numpy as jnp

def build():
    def fn(x):
        return jnp.abs(x)
    return jax.jit(fn)
'''
    diags = audit_trace_source(src, "engine/fixture.py")
    assert any(d.rule_id == "LR304" and "jax_enable_x64" in d.message
               for d in diags)
    # the pin import satisfies the rule…
    assert audit_trace_source(_PINNED + src, "engine/fixture.py") == []
    # …in the package-import spelling the hint suggests too…
    assert audit_trace_source("from arroyo_tpu import ops\n" + src,
                              "engine/fixture.py") == []
    # …and modules under ops/ are the pin itself
    assert audit_trace_source(src, "ops/fixture.py") == []


def test_x64_pinned_at_trace_entry():
    """Regression for the real bug: a cold process importing ONLY
    engine/segment.py (value/key/watermark chain — nothing ever imports
    arroyo_tpu.ops) used to build its trace under default 32-bit jax,
    downcasting every int64 input and failing verification into a
    permanent fallback. _trace_fn must pin x64 before jitting."""
    code = (
        "import arroyo_tpu.engine.segment as seg\n"
        "import jax\n"
        "p = seg._SegmentPlan()\n"
        "seg._trace_fn(p)\n"
        "assert jax.config.jax_enable_x64, 'x64 not pinned at trace entry'\n"
        "print('ok')\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "ok" in out.stdout


# ===================================================== LR305 side effects


def test_lr305_positive_and_negative():
    src = _PINNED + '''
import jax
import jax.numpy as jnp
import logging
import time

_log = logging.getLogger("x")

def build(recorder):
    def fn(x):
        print("tracing")               # trace-time only
        _log.info("batch")             # trace-time only
        t = time.perf_counter()        # trace-time only
        recorder.record("j", "INFO", "X")   # trace-time only
        return jnp.abs(x)
    jitted = jax.jit(fn)
    print("compiled")                  # host side: fine
    _log.info("host")                  # host side: fine
    return jitted
'''
    diags = audit_trace_source(src, "engine/fixture.py")
    assert ids_of(diags) == {"LR305"}
    assert len(diags) == 4
    assert all("trace time" in d.message for d in diags)


# ================================================= waivers & determinism


def test_waiver_grammar():
    src = _PINNED + '''
import jax
import jax.numpy as jnp

def build():
    def fn(x):
        v = float(x)  # lint: waive LR301 — proven scalar aux, synced once
        y = x + 1
        w = int(x)  # lint: waive LR301
        return v, w, y
    return jax.jit(fn)
'''
    diags = audit_trace_source(src, "engine/fixture.py")
    # the justified waiver suppresses; the justification-free one does not
    assert len(diags) == 1 and "int()" in diags[0].message


def test_determinism_and_json_shape():
    d1 = audit_trace_source(LR301_FIXTURE, "engine/fixture.py")
    d2 = audit_trace_source(LR301_FIXTURE, "engine/fixture.py")
    assert d1 == d2 and d1
    assert [d.sort_key() for d in d1] == sorted(d.sort_key() for d in d1)
    payload = json.loads(render_json(d1))
    assert all(set(e) == {"rule", "severity", "site", "message", "hint"}
               for e in payload)


def test_sarif_shape():
    from arroyo_tpu.analysis import Diagnostic

    diags = [
        Diagnostic("LR301", Severity.ERROR, "engine/fixture.py:12", "m", "h"),
        Diagnostic("AR009", Severity.INFO, "a+b+c", "plan finding"),
        Diagnostic("AR007", Severity.WARNING, "src -> dst", "edge finding"),
    ]
    doc = json.loads(render_sarif(diags))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "arroyo-tpu-analysis"
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == \
        {"LR301", "AR009", "AR007"}
    res = {r["ruleId"]: r for r in run["results"]}
    assert res["LR301"]["level"] == "error"
    phys = res["LR301"]["locations"][0]["physicalLocation"]
    assert phys["artifactLocation"]["uri"] == "engine/fixture.py"
    assert phys["region"]["startLine"] == 12
    assert res["AR009"]["level"] == "note"
    assert res["AR009"]["locations"][0]["logicalLocations"][0][
        "fullyQualifiedName"] == "a+b+c"
    assert res["AR007"]["level"] == "warning"


# ============================================== alias-dodge (LR1xx harden)


def test_alias_dodges_do_not_evade_lint():
    src = '''
from jax import jit as J
import jax.numpy as whatever
import numpy as qq
import time as _clock
from time import perf_counter as pc

class Thing:
    def process_batch(self, batch, ctx, collector):
        f = J(lambda c: c)
        dev = whatever.abs(batch)
        host = qq.asarray(dev)
        t = _clock.time()
        t2 = pc()
'''
    diags = lint_source(src, "arroyo_tpu/operators/fixture.py")
    ids = ids_of(diags)
    assert {"LR104", "LR109", "LR111"} <= ids
    assert len([d for d in diags if d.rule_id == "LR109"]) == 2


def test_alias_time_sleep_in_except():
    src = '''
import time as zz

def pull():
    try:
        pass
    except Exception:
        zz.sleep(2)
'''
    diags = lint_source(src, "arroyo_tpu/connectors/fixture.py")
    assert "LR101" in ids_of(diags)


def test_alias_from_import_sleep_in_except():
    """The bare-name dodge: ``from time import sleep as zz`` resolves
    through the alias map even though the call has no receiver."""
    src = '''
from time import sleep as zz

def pull():
    try:
        pass
    except Exception:
        zz(0.5)
'''
    diags = lint_source(src, "arroyo_tpu/connectors/fixture.py")
    assert "LR101" in ids_of(diags)


def test_lr303_annotated_populated_set_is_read():
    """``_KNOWN_DIVERGENT_BINOPS: set[str] = {"**"}`` (annotated AND
    populated) must count as declared — not silently read as empty."""
    seg = SEG_FIXTURE + '_KNOWN_DIVERGENT_BINOPS: set = {"*"}\n'
    ex = EXPR_FIXTURE  # implements '*' both ways, unlisted
    diags = audit_trace_sources([
        (seg, "arroyo_tpu/engine/segment.py"),
        (ex, "arroyo_tpu/expr.py"),
    ])
    assert not any("'*'" in d.message for d in diags), diags


def test_lr304_positional_arange_dtype():
    src = _PINNED + '''
import jax
import jax.numpy as jnp

def build():
    def fn(n):
        return jnp.arange(0, n, 1, jnp.int64)   # positional dtype: fine
    return jax.jit(fn)
'''
    assert audit_trace_source(src, "engine/fixture.py") == []


# =========================================================== repo gates


def test_repo_trace_audit_clean():
    """The acceptance gate: LR301–LR305 over the whole package, zero
    unwaived findings — every real sweep finding is fixed in-code."""
    diags = lint_paths([PKG_DIR], root=os.path.dirname(PKG_DIR))
    lr3 = [d for d in diags if d.rule_id.startswith("LR3")]
    assert lr3 == [], "\n".join(d.render() for d in lr3)


def test_rules_registered():
    from arroyo_tpu.analysis import TRACE_RULES

    assert TRACE_RULES == ("LR301", "LR302", "LR303", "LR304", "LR305")


# ====================================================== the parity oracle


def _values(dt) -> np.ndarray:
    dt = np.dtype(dt)
    if dt.kind == "f":
        return np.array([np.nan, np.inf, -np.inf, -0.0, 0.0, 1.5, -2.25,
                         np.finfo(dt).max, np.finfo(dt).tiny], dtype=dt)
    if dt.kind == "b":
        return np.array([True, False], dtype=dt)
    info = np.iinfo(dt)
    vals = [info.min, info.max, 0, 7]
    if dt.kind == "i":
        vals.append(-1)
    return np.array(vals, dtype=dt)


def _pairs(dt_l, dt_r, nonzero_right=False):
    a = _values(dt_l)
    b = _values(dt_r)
    if nonzero_right:
        b = b[(b != 0) & np.isfinite(b.astype(np.float64, copy=False)
                                     if np.dtype(dt_r).kind == "f" else b)]
    l, r = np.meshgrid(a, b)
    return l.ravel(), r.ravel()


def _jit_expr(expr, names, arrays):
    import jax

    from arroyo_tpu.ops import require_x64

    require_x64()

    def fn(*arrs):
        return expr.eval_jnp(dict(zip(names, arrs)))

    return np.asarray(jax.jit(fn)(*arrays))


def _assert_parity(expr, names, arrays, label):
    from arroyo_tpu.expr import eval_expr

    n = len(arrays[0]) if arrays else 0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        want = np.asarray(eval_expr(expr, dict(zip(names, arrays)), n))
        got = _jit_expr(expr, names, arrays)
    assert got.dtype == want.dtype, \
        f"{label}: dtype {got.dtype} != {want.dtype}"
    assert got.tobytes() == want.tobytes(), f"{label}: values differ"


NUMERIC = ("int32", "int64", "uint64", "float32", "float64")
# mixed pairs whose promotion CONVERGES across the two paths (int×float32
# deliberately absent: that is the divergence AR009 rejects at plan time)
CONVERGENT_MIXED = (("int32", "int64"), ("int64", "float64"),
                    ("float32", "float64"), ("uint64", "float64"))


@pytest.mark.parametrize("op", ["+", "-", "*"])
def test_oracle_arithmetic(op):
    from arroyo_tpu.expr import BinOp, Col

    e = BinOp(op, Col("l"), Col("r"))
    for dl, dr in [(d, d) for d in NUMERIC] + list(CONVERGENT_MIXED):
        l, r = _pairs(dl, dr)
        _assert_parity(e, ("l", "r"), [l, r], f"{dl} {op} {dr}")
        _assert_parity(e, ("l", "r"),
                       [np.empty(0, dl), np.empty(0, dr)],
                       f"{dl} {op} {dr} empty")


@pytest.mark.parametrize("op", ["/", "%"])
def test_oracle_division(op):
    """Division/modulo parity — including the float-mod signed-zero fix
    this oracle caught (np.mod gives exact-zero remainders the DIVISOR's
    sign, XLA the dividend's; expr._mod_jnp patches the cells). Cells
    whose numpy result is SUBNORMAL are excluded: XLA on CPU flushes
    denormals to zero (FTZ) and no in-repo fix exists — the documented
    parity caveat in the README."""
    from arroyo_tpu.expr import BinOp, Col, eval_expr

    e = BinOp(op, Col("l"), Col("r"))
    for dl, dr in [(d, d) for d in NUMERIC]:
        l, r = _pairs(dl, dr, nonzero_right=True)
        if op == "/" and np.dtype(dl).kind == "i":
            # exercise the floor->trunc sign correction without the one
            # UB cell (INT_MIN / -1 overflows differently per backend)
            keep = ~((l == np.iinfo(dl).min) & (r == -1))
            l, r = l[keep], r[keep]
        if np.dtype(dl).kind == "f":
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                want = np.asarray(eval_expr(e, {"l": l, "r": r}, len(l)))
            tiny = np.finfo(dl).tiny  # smallest NORMAL magnitude
            subnormal = (np.abs(want) > 0) & (np.abs(want) < tiny)
            l, r = l[~subnormal], r[~subnormal]
        _assert_parity(e, ("l", "r"), [l, r], f"{dl} {op} {dr}")
        _assert_parity(e, ("l", "r"),
                       [np.empty(0, dl), np.empty(0, dr)],
                       f"{dl} {op} {dr} empty")


@pytest.mark.parametrize("op", ["==", "!=", "<", "<=", ">", ">="])
def test_oracle_comparisons(op):
    from arroyo_tpu.expr import BinOp, Col

    e = BinOp(op, Col("l"), Col("r"))
    for d in NUMERIC + ("bool",):
        l, r = _pairs(d, d)
        _assert_parity(e, ("l", "r"), [l, r], f"{d} {op} {d}")
    _assert_parity(e, ("l", "r"),
                   [np.empty(0, np.float64), np.empty(0, np.float64)],
                   f"{op} empty")


@pytest.mark.parametrize("op", ["and", "or"])
def test_oracle_logic(op):
    from arroyo_tpu.expr import BinOp, Col

    e = BinOp(op, Col("l"), Col("r"))
    l, r = _pairs("bool", "bool")
    _assert_parity(e, ("l", "r"), [l, r], f"bool {op} bool")


@pytest.mark.parametrize("name", ["abs", "floor", "ceil", "sqrt"])
def test_oracle_float_funcs(name):
    """floor/ceil/sqrt over INTEGER inputs is the regression lock for the
    second real sweep finding: jnp left floor/ceil of ints as ints (and
    computed sqrt(int32) in float32) where numpy promotes to float64 —
    every such segment failed first-batch verification. eval_jnp now
    promotes explicitly; bool inputs remain divergent and are rejected at
    plan time by AR009 (not swept here)."""
    from arroyo_tpu.expr import Col, Func

    e = Func(name, (Col("a"),))
    for d in NUMERIC:
        if name == "abs" and d == "uint64":
            pass  # np.abs(uint64) is identity; still worth sweeping
        _assert_parity(e, ("a",), [_values(d)], f"{name}({d})")
        _assert_parity(e, ("a",), [np.empty(0, d)], f"{name}({d}) empty")


def test_oracle_time_funcs():
    from arroyo_tpu.expr import Col, Func, Lit

    ts = np.array([0, 1, -1, 1_234_567_890_123, -7_200_000_001,
                   np.iinfo(np.int64).max // 2], dtype=np.int64)
    _assert_parity(Func("extract_epoch", (Col("a"),)), ("a",), [ts],
                   "extract_epoch(int64)")
    _assert_parity(Func("date_trunc_micros", (Lit(60_000_000), Col("a"))),
                   ("a",), [ts], "date_trunc_micros")
    _assert_parity(Func("to_timestamp_micros", (Col("a"),)), ("a",), [ts],
                   "to_timestamp_micros(int64)")
    _assert_parity(Func("to_timestamp_micros", (Col("a"),)), ("a",),
                   [np.array([1, 2, 3], dtype=np.int32)],
                   "to_timestamp_micros(int32)")


def test_oracle_composed_case_cast_neg_not():
    """The remaining traceable node kinds, composed, across the matrix."""
    from arroyo_tpu.expr import BinOp, Case, Cast, Col, Lit, Neg, Not

    case = Case(((BinOp(">", Col("a"), Lit(0)), Col("a")),),
                Neg(Col("a")))
    for d in ("int32", "int64", "float32", "float64"):
        _assert_parity(case, ("a",), [_values(d)], f"case({d})")
    cast = Cast(BinOp("+", Col("a"), Lit(1)), "float64")
    for d in ("int32", "int64", "float64"):
        _assert_parity(cast, ("a",), [_values(d)], f"cast({d})")
    notb = Not(BinOp("<", Col("a"), Lit(3)))
    _assert_parity(notb, ("a",), [_values("int64")], "not(<)")


# ========================================================== AR009 (plan)


def _register_smoke():
    smoke = os.path.join(os.path.dirname(__file__), "smoke")
    sys.path.insert(0, smoke)
    try:
        import udfs  # noqa: F401
    finally:
        sys.path.pop(0)


def _sql(select: str, cols: str = "a BIGINT NOT NULL, b REAL NOT NULL"):
    return f'''
CREATE TABLE src ({cols}) WITH (
  connector = 'single_file', path = '/dev/null',
  format = 'json', type = 'source'
);
CREATE TABLE out (x DOUBLE) WITH (
  connector = 'single_file', path = '/tmp/ar009_out.json',
  format = 'json', type = 'sink'
);
INSERT INTO out SELECT {select} FROM src;
'''


def test_ar009_rejects_int_float32_divergence():
    """BIGINT * REAL: numpy widens to float64, the jax lattice stays
    float32 — the one promotion corner where the paths split. Rejected at
    plan time instead of failing first-batch verification at runtime."""
    arroyo_tpu._load_operators()
    pp, diags = check_sql(_sql("a * b"))
    errs = [d for d in diags if d.severity == Severity.ERROR]
    assert any(d.rule_id == "AR009" for d in errs)
    msg = next(d.message for d in errs if d.rule_id == "AR009")
    assert "float64" in msg and "float32" in msg


def test_ar009_explicit_cast_is_clean():
    arroyo_tpu._load_operators()
    pp, diags = check_sql(_sql("a * CAST(b AS DOUBLE)"))
    assert not any(d.rule_id == "AR009" and d.severity == Severity.ERROR
                   for d in diags)
    assert pp is not None


def test_ar009_not_compilable_reason_is_surfaced():
    """A chain the optimizer declines to mark (concat is host-only, so
    the traceable prefix is too short) carries its ``not compilable:``
    reason as an INFO diagnostic in check."""
    arroyo_tpu._load_operators()
    pp, diags = check_sql(
        _sql("concat('p_', s)", cols="s TEXT, a BIGINT NOT NULL"))
    infos = [d for d in diags
             if d.rule_id == "AR009" and d.severity == Severity.INFO]
    assert any("not compilable" in d.message for d in infos), diags


def test_ar009_jnp_dtype_model_matches_real_jax():
    """The static jnp dtype model behind AR009, pinned against REAL jitted
    dtypes — if jax promotion semantics drift, this fails before the
    model silently mis-judges pipelines."""
    import jax

    from arroyo_tpu.analysis.trace_audit import _jnp_dtype, _resolve_weak
    from arroyo_tpu.expr import BinOp, Case, Cast, Col, Func, Lit

    from arroyo_tpu.ops import require_x64

    require_x64()

    cases = [
        (BinOp("*", Col("a"), Col("b")),
         {"a": np.dtype(np.int64), "b": np.dtype(np.float32)}),
        (BinOp("+", Col("a"), Lit(2)), {"a": np.dtype(np.int32)}),
        (BinOp("+", Col("a"), Lit(2.5)), {"a": np.dtype(np.uint64)}),
        (BinOp("/", Col("a"), Col("b")),
         {"a": np.dtype(np.int64), "b": np.dtype(np.int64)}),
        (BinOp("/", Col("a"), Lit(2.0)), {"a": np.dtype(np.int64)}),
        (BinOp("<", Col("a"), Lit(0)), {"a": np.dtype(np.float32)}),
        (Func("sqrt", (Col("a"),)), {"a": np.dtype(np.int32)}),
        (Func("floor", (Col("a"),)), {"a": np.dtype(np.int64)}),
        (Func("sqrt", (Col("a"),)), {"a": np.dtype(np.bool_)}),
        (Func("extract_epoch", (Col("a"),)), {"a": np.dtype(np.int64)}),
        (Cast(Col("a"), "int32"), {"a": np.dtype(np.int64)}),
        (Case(((BinOp(">", Col("a"), Lit(0)), Col("a")),), Lit(0.5)),
         {"a": np.dtype(np.int64)}),
        (Func("to_timestamp_micros", (Col("a"),)),
         {"a": np.dtype(np.int32)}),
    ]
    for expr, env in cases:
        names = sorted(env)

        def fn(*arrs):
            return expr.eval_jnp(dict(zip(names, arrs)))

        real = np.asarray(jax.jit(fn)(
            *[np.ones(2, dtype=env[n]) for n in names])).dtype
        modeled = np.dtype(_resolve_weak(_jnp_dtype(expr, env)))
        assert modeled == real, f"{expr}: model {modeled} != real {real}"


def test_queries_bad_fixture_registered():
    """The catalog entry exists and carries the AR009 annotation (the
    parametrized catalog test in test_analysis.py executes it)."""
    p = os.path.join(os.path.dirname(__file__), "smoke", "queries_bad",
                     "segment_dtype_divergence.sql")
    with open(p) as f:
        assert f.read().startswith("-- reject: AR009")


# ============================================= not-compilable surfacing


def test_segment_reject_reason():
    from arroyo_tpu.engine.segment import (segment_marking,
                                           segment_reject_reason)
    from arroyo_tpu.expr import BinOp, Col, Lit

    traceable = [("value", {"projections": [("x", BinOp("+", Col("x"),
                                                        Lit(1)))]}),
                 ("watermark", {"expr": Col("_timestamp")})]
    assert segment_marking(traceable) is not None
    assert segment_reject_reason(traceable) is None

    short = [("value", {"projections": [("x", Col("x"))]}),
             ("sink", {})]
    assert segment_marking(short) is None
    reason = segment_reject_reason(short)
    assert reason is not None and reason.startswith("not compilable:")
    # the STOP reason leads the string so truncating renderers (top's
    # 48-char cell) keep the actionable part, not the boilerplate
    assert "sink" in reason[:48]


def test_executed_graph_view_not_compilable():
    from arroyo_tpu import config as cfg
    from arroyo_tpu.sql.planner import executed_graph_view

    arroyo_tpu._load_operators()
    _register_smoke()
    cfg.update({"pipeline.chaining.enabled": True})
    try:
        nodes, _edges = executed_graph_view(
            _sql("concat('p_', s)", cols="s TEXT, a BIGINT NOT NULL"))
    finally:
        cfg.update({"pipeline.chaining.enabled": False})
    reasons = [n.get("not_compilable") for n in nodes
               if n.get("not_compilable")]
    assert reasons and all(r.startswith("not compilable:")
                           for r in reasons)


def test_explain_and_top_render_not_compiled():
    from arroyo_tpu.obs.profile import render_explain
    from arroyo_tpu.obs.topview import render

    profile = {"chain_1": {"busy_pct": 12.0, "late_rows": 0,
                           "segment_reason": "not compilable: operator "
                                             "sink is not traceable"}}
    nodes = [{"id": "chain_1", "op": "chained", "parallelism": 1}]
    text = render_explain(nodes, [], profile, {"id": "j1", "state": "Running"})
    assert "[not compiled: not compilable: operator sink" in text

    # a plan-only node (no profile yet) still explains itself
    nodes2 = [{"id": "chain_2", "op": "chained", "parallelism": 1,
               "not_compilable": "not compilable: x"}]
    text2 = render_explain(nodes2, [], {}, None)
    assert "[not compilable: x]" in text2

    metrics = {"chain_1": {"subtasks": 1, "messages_per_sec": 0.0,
                           "segment_reason": "verification failed: x"}}
    frame = render(
        {"id": "j1", "state": "Running", "n_workers": 1}, metrics)
    assert "[not compiled: verification failed: x]" in frame

    # a realistic plan-time reject: top must keep the stop reason inside
    # its truncated cell, not just the boilerplate prefix
    metrics2 = {"chain_1": {
        "subtasks": 1, "messages_per_sec": 0.0,
        "segment_reason": "not compilable: operator sink is not "
                          "traceable (traceable prefix 1 < 2)"}}
    frame2 = render(
        {"id": "j1", "state": "Running", "n_workers": 1}, metrics2)
    assert "[not compiled: operator sink is not traceable" in frame2


def test_runner_for_copies_reject_reason():
    """metrics.segment_reason carries the plan-time reject so top/explain
    explain interpreted chains without waiting for a runtime event."""
    from arroyo_tpu.engine.segment import runner_for
    from arroyo_tpu.metrics import TaskMetrics
    from arroyo_tpu.operators.chained import ChainedOperator

    arroyo_tpu._load_operators()
    from arroyo_tpu import config as cfg

    cfg.update({"segment.compile.enabled": True})
    op = ChainedOperator({
        "members": [("value", {"projections": None, "filter": None}),
                    ("watermark", {"expr": None})],
        "compile_reject": "not compilable: fixture reason",
    })
    m = TaskMetrics("j", "n", 0)
    assert runner_for(op, None, m) is None
    assert m.segment_reason == "not compilable: fixture reason"


# ============================================= shard_map roots (mesh fusion)


def test_shard_map_is_a_jit_root():
    """A function handed to shard_map runs traced per-shard even when no
    jit() call wraps it in the same module (engine/segment.py jits the
    composed program elsewhere) — the walker must treat the shard_map call
    site as a root or the fused mesh step escapes LR301-LR305 entirely."""
    src = _PINNED + '''
import jax
from jax.experimental.shard_map import shard_map

def build(mesh, specs):
    def step(state, x):
        float(x)                        # host sync on traced
        return state, x
    return shard_map(step, mesh, in_specs=specs, out_specs=specs)
'''
    diags = audit_trace_source(src, "engine/fixture.py")
    assert ids_of(diags) == {"LR301"}


def test_shard_map_compat_alias_is_a_jit_root():
    """The repo's version-compat alias (parallel/sharded_agg.py imports it
    as ``_shard_map``) must not dodge root discovery: leading underscores
    are stripped before the name check."""
    src = _PINNED + '''
import jax
from jax.experimental.shard_map import shard_map as _shard_map

def build(mesh, specs):
    def step(state, x):
        if x > 0:                       # if on traced
            pass
        return state, x
    return jax.jit(_shard_map(step, mesh, in_specs=specs, out_specs=specs))
'''
    diags = audit_trace_source(src, "engine/fixture.py")
    assert ids_of(diags) == {"LR301"}


def test_shard_map_clean_body_is_clean():
    """Negative control: a pure per-shard body through the same wrapper
    produces no findings (the root is walked, and passes)."""
    src = _PINNED + '''
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map

def build(mesh, specs):
    def step(state, x):
        return state, jnp.where(x > 0, x, 0)
    return shard_map(step, mesh, in_specs=specs, out_specs=specs)
'''
    assert audit_trace_source(src, "engine/fixture.py") == []
