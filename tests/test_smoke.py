"""SQL smoke tests: the backbone harness.

Mirror of the reference's arroyo-sql-testing suite (SURVEY §4.1,
smoke_tests.rs:33-436): every query in tests/smoke/queries runs three ways —
(a) to completion at parallelism 1;
(b) at parallelism 2 with checkpoints at epochs 1-3, stopping at epoch 3;
(c) restored from epoch 3 at parallelism 3, run to completion —
and the output is diffed (order-insensitive; updating streams are
debezium-merged first) against golden files produced by independent oracles
(tests/smoke/generate.py).
"""

from __future__ import annotations

import glob
import json
import os

import pytest

SMOKE = os.path.join(os.path.dirname(__file__), "smoke")
QUERIES = sorted(
    os.path.splitext(os.path.basename(p))[0]
    for p in glob.glob(os.path.join(SMOKE, "queries", "*.sql"))
)


def load_sql(name: str, output_path: str) -> str:
    with open(os.path.join(SMOKE, "queries", f"{name}.sql")) as f:
        sql = f.read()
    return sql.replace("$input_dir", os.path.join(SMOKE, "inputs")).replace(
        "$output_path", output_path
    )


def is_updating(name: str) -> bool:
    import sys

    sys.path.insert(0, SMOKE)
    try:
        from generate import UPDATING  # type: ignore

        return name in UPDATING
    finally:
        sys.path.pop(0)


def canon(row: dict) -> str:
    """Canonical form for order-insensitive multiset comparison; floats
    rounded so summation order doesn't flip the diff."""
    out = {}
    for k, v in sorted(row.items()):
        if isinstance(v, float):
            v = round(v, 6)
        out[k] = v
    return json.dumps(out, sort_keys=True)


def merge_debezium(lines: list[dict]) -> list[dict]:
    """Apply retract/append envelopes to a multiset (reference
    smoke_tests.rs:475-521 merge_debezium)."""
    counts: dict[str, int] = {}
    rows: dict[str, dict] = {}
    for obj in lines:
        if "op" not in obj:
            key = canon(obj)
            counts[key] = counts.get(key, 0) + 1
            rows[key] = obj
            continue
        if obj["op"] in ("c", "r"):
            row = obj["after"]
            key = canon(row)
            counts[key] = counts.get(key, 0) + 1
            rows[key] = row
        elif obj["op"] == "d":
            row = obj["before"]
            key = canon(row)
            if key not in counts:
                raise AssertionError(f"retract of unseen row: {row}")
            counts[key] -= 1
            if counts[key] == 0:
                del counts[key]
        elif obj["op"] == "u":
            bkey = canon(obj["before"])
            counts[bkey] = counts.get(bkey, 0) - 1
            if counts.get(bkey) == 0:
                del counts[bkey]
            row = obj["after"]
            key = canon(row)
            counts[key] = counts.get(key, 0) + 1
            rows[key] = row
    out = []
    for key, n in counts.items():
        out.extend([rows[key]] * n)
    return out


def read_output(path: str) -> list[dict]:
    lines: list[dict] = []
    for p in sorted(glob.glob(path) + glob.glob(path + ".*")):
        with open(p) as f:
            for line in f:
                if line.strip():
                    lines.append(json.loads(line))
    return lines


def assert_outputs(name: str, output_path: str):
    golden_path = os.path.join(SMOKE, "golden", f"{name}.json")
    with open(golden_path) as f:
        golden = [json.loads(l) for l in f if l.strip()]
    got = read_output(output_path)
    if is_updating(name):
        got = merge_debezium(got)
    got_c = sorted(canon(r) for r in got)
    want_c = sorted(canon(r) for r in golden)
    assert got_c == want_c, (
        f"{name}: output mismatch ({len(got_c)} rows vs {len(want_c)} golden)\n"
        f"extra:   {[r for r in got_c if r not in want_c][:5]}\n"
        f"missing: {[r for r in want_c if r not in got_c][:5]}"
    )


def build(sql: str, parallelism: int, job_id: str, restore_epoch=None):
    import sys

    sys.path.insert(0, SMOKE)
    try:
        import udfs  # noqa: F401  (registers the suite's test UDAFs)
    finally:
        sys.path.pop(0)
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.sql import plan_query
    from arroyo_tpu.sql.planner import set_parallelism

    pp = plan_query(sql)
    if parallelism > 1:
        set_parallelism(pp.graph, parallelism)
    eng = Engine(pp.graph, job_id=job_id, restore_epoch=restore_epoch)
    return eng


def build_two_workers(graph_json: str, job_id: str, restore_epoch=None,
                      coordinate: bool = False):
    """Split a planned graph across two in-process Engines joined by the
    TCP data plane: source nodes on worker 0, everything else on worker 1
    (guarantees remote edges for the partition chaos axis).

    Engines under an assignment are pure 2PC participants — they relay
    acks upward and only complete epochs on an injected commit — so runs
    that take checkpoints need ``coordinate=True`` to attach the
    controller-style EngineSetCoordinator (it writes the job-level
    metadata marker at global coverage and fans commits back)."""
    from arroyo_tpu.controller.checkpoint_state import EngineSetCoordinator
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.engine.network import NetworkManager
    from arroyo_tpu.graph import Graph

    g = Graph.loads(graph_json)
    assignment = {}
    for nid, node in g.nodes.items():
        w = 0 if not g.in_edges(nid) else 1
        for s in range(node.parallelism):
            assignment[(nid, s)] = w
    nm0, nm1 = NetworkManager(), NetworkManager()
    peers = {0: ("127.0.0.1", nm0.port), 1: ("127.0.0.1", nm1.port)}
    nm0.set_peers(peers)
    nm1.set_peers(peers)
    w0 = Engine(Graph.loads(graph_json), job_id=job_id, assignment=assignment,
                worker_index=0, network=nm0, restore_epoch=restore_epoch)
    w1 = Engine(Graph.loads(graph_json), job_id=job_id, assignment=assignment,
                worker_index=1, network=nm1, restore_epoch=restore_epoch)
    coord = EngineSetCoordinator([w0, w1]).start() if coordinate else None
    return (w0, w1), (nm0, nm1), coord


def wait_epoch(engine, epoch: int, timeout: float = 60.0) -> bool:
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with engine._lock:
            if epoch in engine._completed_epochs:
                return True
            if engine._failed:
                return False
        time.sleep(0.05)
    return False


# ---------------------------------------------------------------- chaos axis
#
# Exactly-once proved, not claimed: rerun golden-output families while
# killing a worker mid-checkpoint, partitioning the data plane mid-stream,
# and failing storage mid-compaction — recovery must still be byte-exact.
# The fault plan + seed print on failure (conftest) so runs are replayable.

CHAOS_FAMILIES = ["select_star", "tumbling_aggregates", "sliding_window"]
CHAOS_SEED = 1337


def assert_commit_after_durable(event_log):
    """The distributed-2PC safety invariant: no phase-2 commit may ever be
    sent for an epoch before that epoch's job-level metadata is durable
    across ALL workers (the coordinator appends to this ordered log)."""
    durable_at: dict[int, int] = {}
    commits = 0
    for i, ev in enumerate(event_log):
        if ev[0] == "metadata_durable":
            durable_at.setdefault(ev[1], i)
        elif ev[0] in ("commit_sent", "commit_dropped"):
            commits += 1
            assert ev[1] in durable_at and durable_at[ev[1]] < i, (
                f"commit for epoch {ev[1]} at log[{i}] precedes its "
                f"metadata durability: {event_log}")
    assert commits, f"no commits were ever fanned out: {event_log}"


def assert_fsck_clean(job_id):
    """Post-chaos invariant: whatever the fault tore, the surviving
    checkpoint chain must fsck clean — no FS-series ERROR (torn epochs and
    GC-owned debris are warnings by design; actual corruption is not)."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.analysis import Severity
    from arroyo_tpu.state.integrity import fsck_job

    storage_url = cfg.config().get("checkpoint.storage-url")
    errs = [d.render() for d in fsck_job(storage_url, job_id)
            if d.severity == Severity.ERROR]
    assert not errs, f"post-chaos fsck found corruption: {errs}"


@pytest.mark.chaos
@pytest.mark.parametrize("name", CHAOS_FAMILIES)
def test_chaos_worker_crash_mid_checkpoint(name, tmp_path, _storage):
    """Crash the first subtask to reach barrier 2 AFTER its epoch-2 state
    files land but before the epoch completes: the torn epoch must be
    ignored and recovery from epoch 1 must reproduce the goldens."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.state.tables import latest_complete_checkpoint

    out = str(tmp_path / "out.json")
    sql = load_sql(name, out)
    job_id = f"{name}-chaos-crash"
    cfg.update({"testing.source-gate-epochs": 2})
    inj = faults.install("worker:crash@barrier=2&step=1", seed=CHAOS_SEED)
    try:
        eng = build(sql, 2, job_id)
        eng.start()
        assert eng.checkpoint_and_wait(1, timeout=60), "epoch 1 did not complete"
        with pytest.raises(RuntimeError, match="injected"):
            if eng.checkpoint_and_wait(2, timeout=60):
                raise AssertionError("epoch 2 completed despite injected crash")
            eng.join(timeout=60)
    finally:
        faults.clear()
        cfg.update({"testing.source-gate-epochs": 0})
    assert inj.fired_log, "crash fault never fired"
    storage_url = cfg.config().get("checkpoint.storage-url")
    assert latest_complete_checkpoint(storage_url, job_id) == 1

    eng2 = build(sql, 2, job_id, restore_epoch=1)
    eng2.run_to_completion(timeout=180)
    assert_outputs(name, out)
    assert_fsck_clean(job_id)


@pytest.mark.chaos
@pytest.mark.parametrize("name", CHAOS_FAMILIES)
def test_chaos_dataplane_partition_mid_stream(name, tmp_path, _storage):
    """Partition the TCP data plane mid-stream (sources gated mid-file, so
    windows are open): the sending worker dies, and a two-worker restore
    from the last complete epoch reproduces the goldens."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.sql import plan_query
    from arroyo_tpu.sql.planner import set_parallelism
    from arroyo_tpu.state.tables import latest_complete_checkpoint

    out = str(tmp_path / "out.json")
    sql = load_sql(name, out)
    job_id = f"{name}-chaos-net"
    import sys

    sys.path.insert(0, SMOKE)
    try:
        import udfs  # noqa: F401
    finally:
        sys.path.pop(0)
    pp = plan_query(sql)
    set_parallelism(pp.graph, 2)
    graph_json = pp.graph.dumps()

    cfg.update({"testing.source-gate-epochs": 2})
    (w0, w1), (nm0, nm1), coord = build_two_workers(graph_json, job_id,
                                                    coordinate=True)
    try:
        w1.build()
        w0.build()
        w1.start()
        w0.start()
        assert w0.checkpoint_and_wait(1, timeout=60), "epoch 1 did not complete"
        assert wait_epoch(w1, 1), "worker 1 never finished epoch 1"
        inj = faults.install("network.send:partition@after=1", seed=CHAOS_SEED)
        w0.trigger_checkpoint(2)  # the barrier's wire crossing hits the cut
        with pytest.raises(RuntimeError, match="partition"):
            w0.join(timeout=90)
        assert inj.fired_log, "partition fault never fired"
    finally:
        faults.clear()
        cfg.update({"testing.source-gate-epochs": 0})
        w1._abort()
        try:
            w1.join(timeout=30)
        except RuntimeError:
            pass  # receiver-side tasks may also report the cut
        coord.stop()
        nm0.close()
        nm1.close()

    storage_url = cfg.config().get("checkpoint.storage-url")
    assert latest_complete_checkpoint(storage_url, job_id) == 1
    # torn epoch 2 must never have gone durable, and the 2PC trail must show
    # metadata durability strictly preceding every commit for epoch 1
    assert_commit_after_durable(coord.event_log)
    assert all(ev[1] == 1 for ev in coord.event_log
               if ev[0] in ("metadata_durable", "commit_sent"))

    (r0, r1), (rm0, rm1), _ = build_two_workers(graph_json, job_id, restore_epoch=1)
    try:
        r1.build()
        r0.build()
        r1.start()
        r0.start()
        r0.join(timeout=180)
        r1.join(timeout=180)
    finally:
        rm0.close()
        rm1.close()
    assert_outputs(name, out)
    assert_fsck_clean(job_id)


@pytest.mark.chaos
@pytest.mark.parametrize("name", CHAOS_FAMILIES)
def test_chaos_worker_set_crash_mid_checkpoint(name, tmp_path, _storage):
    """2-worker assignment axis: a controller-supervised worker SET
    (controller.workers-per-job=2, subtasks round-robined across both,
    remote edges over the TCP data plane) loses one worker to a crash
    mid-epoch-2 — after its shards land but before the epoch is globally
    durable. The controller must kill the whole set, restore BOTH workers
    from the last globally complete checkpoint, and reproduce the goldens
    byte-exact; the coordinator's ordered event log must show job-level
    metadata durable before every phase-2 commit."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler

    out = str(tmp_path / "out.json")
    sql = load_sql(name, out)
    db = Database()
    cfg.update({
        "controller.workers-per-job": 2,
        "checkpoint.interval-ms": 150,
        "testing.source-read-delay-micros": 4000,
    })
    inj = faults.install("worker:crash@barrier=2&step=1", seed=CHAOS_SEED)
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline(name, sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        jc = ctl.jobs[jid]  # survives recovery; holds the 2PC event log
        import time as _time

        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline and len(jc.handles) != 2:
            _time.sleep(0.05)  # may race the crash/recovery window
        assert len(jc.handles) == 2, "worker set never reached 2 workers"
        state = ctl.wait_for_state(jid, "Finished", timeout=180)
        assert state == "Finished"
        job = db.get_job(jid)
        assert int(job["restarts"]) >= 1, "the crashed set was never restored"
        assert int(job["n_workers"]) == 2
    finally:
        faults.clear()
        cfg.update({"controller.workers-per-job": 1,
                    "checkpoint.interval-ms": 10_000,
                    "testing.source-read-delay-micros": 0})
        ctl.stop()
    assert inj.fired_log, "crash fault never fired"
    # no commit ever preceded its epoch's global durability — across BOTH
    # worker-set incarnations (the log survives the restore)
    assert_commit_after_durable(jc.checkpoint_event_log)
    assert_outputs(name, out)
    assert_fsck_clean(jid)


@pytest.mark.chaos
@pytest.mark.parametrize("name", CHAOS_FAMILIES)
def test_chaos_storage_fail_mid_compaction(name, tmp_path, _storage):
    """Two storage-failure proofs on one run: (a) a transient put failure
    during the epoch-2 checkpoint recovers in place through the shared
    retry layer — no job restart; (b) compaction torn mid-metadata-rewrite
    (after the generation-1 commit point) still restores byte-exact."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults

    out = str(tmp_path / "out.json")
    sql = load_sql(name, out)
    job_id = f"{name}-chaos-storage"
    cfg.update({"testing.source-gate-epochs": 2})
    inj = faults.install("storage.put:fail_once@match=checkpoint-0000002",
                         seed=CHAOS_SEED)
    try:
        eng = build(sql, 2, job_id)
        eng.start()
        assert eng.checkpoint_and_wait(1, timeout=60)
        assert eng.checkpoint_and_wait(2, timeout=60, then_stop=True)
        eng.join(timeout=120)
    finally:
        faults.clear()
        cfg.update({"testing.source-gate-epochs": 0})
    assert inj.fired_log, "transient storage fault never fired"

    # tear compaction after the g1-holder metadata (the commit point) lands
    inj2 = faults.install("storage.put:fail@match=metadata-&after=2",
                          seed=CHAOS_SEED)
    try:
        with pytest.raises(RuntimeError, match="injected"):
            eng.compact(2)
    finally:
        faults.clear()
    assert inj2.fired_log, "compaction tear never fired"

    # the torn epoch (merged g1 file + stale gen-0 shards both on disk)
    # must restore without loss or double-counted state
    eng2 = build(sql, 2, job_id, restore_epoch=2)
    eng2.run_to_completion(timeout=180)
    assert_outputs(name, out)
    assert_fsck_clean(job_id)


# ------------------------------------------------------------- fail cases
#
# Mirror of the reference's --fail SQL tests (arroyo-sql-testing, e.g.
# most_active_driver_last_hour_unaligned.sql): every 'reject'-annotated
# pipeline in tests/smoke/queries_bad must be refused AT PLAN TIME — by the
# planner itself or by the static analyzer (arroyo_tpu.analysis) that runs
# at the end of plan_query — never deferred to a runtime blow-up.

FAIL_QUERIES = sorted(
    os.path.splitext(os.path.basename(p))[0]
    for p in glob.glob(os.path.join(SMOKE, "queries_bad", "*.sql"))
    if open(p).readline().startswith("-- reject")
)


@pytest.mark.parametrize("name", FAIL_QUERIES)
def test_smoke_fail(name, tmp_path):
    import re
    import sys

    from arroyo_tpu.sql import plan_query
    from arroyo_tpu.sql.lexer import SqlError

    # register the suite's fixture UDFs/connectors (duplicate_table_specs
    # plans the deliberately-broken 'bad_state' connector): without this,
    # standalone runs of this file would skip AR008's node and not reject
    sys.path.insert(0, SMOKE)
    try:
        import udfs  # noqa: F401
    finally:
        sys.path.pop(0)

    path = os.path.join(SMOKE, "queries_bad", f"{name}.sql")
    with open(path) as f:
        text = f.read()
    rule = re.match(r"--\s*reject:\s*(\S+)", text).group(1)
    sql = text.replace("$input_dir", os.path.join(SMOKE, "inputs")).replace(
        "$output_path", str(tmp_path / "out.json"))
    with pytest.raises(SqlError) as ei:
        plan_query(sql)
    if rule != "AR000":  # AR000 = rejected by the planner itself
        assert rule in str(ei.value), (
            f"{name}: expected rule {rule} in error, got: {ei.value}")


@pytest.mark.parametrize("chaining", [False, True], ids=["unchained", "chained"])
@pytest.mark.parametrize("name", QUERIES)
def test_smoke(name, chaining, tmp_path, _storage):
    from arroyo_tpu import config as cfg

    cfg.update({"pipeline.chaining.enabled": chaining})

    # ---- run 1: parallelism 1, to completion --------------------------
    out1 = str(tmp_path / "out1.json")
    eng = build(load_sql(name, out1), 1, f"{name}-p1")
    eng.run_to_completion(timeout=180)
    assert_outputs(name, out1)

    # ---- run 2: parallelism 2, checkpoints 1-3, stop at 3 -------------
    # the source gate holds every source mid-file until 3 barriers have
    # passed, so the mid-stream stop is deterministic (never silently
    # degrades to a completed run; reference smoke_tests.rs:300-356)
    out2 = str(tmp_path / "out2.json")
    sql2 = load_sql(name, out2)
    cfg.update({"testing.source-gate-epochs": 3})
    try:
        eng2 = build(sql2, 2, f"{name}-ckpt")
        eng2.start()
        for epoch in (1, 2):
            assert eng2.checkpoint_and_wait(epoch, timeout=60), (
                f"checkpoint epoch {epoch} did not complete mid-stream"
            )
            if epoch == 2:
                # reference runs state compaction after epoch 2
                eng2.compact(2)
        assert eng2.checkpoint_and_wait(3, timeout=60, then_stop=True), (
            "checkpoint epoch 3 (stopping) did not complete mid-stream"
        )
        eng2.join(timeout=120)
    finally:
        cfg.update({"testing.source-gate-epochs": 0})

    # ---- run 3: restore from epoch 3 at parallelism 3, finish ---------
    # compact the restore epoch + GC older epochs first: restore must
    # work from compacted generation-1 files alone
    eng2.compact(3)
    eng2.cleanup(min_epoch=3)
    eng3 = build(sql2, 3, f"{name}-ckpt", restore_epoch=3)
    eng3.run_to_completion(timeout=180)
    assert_outputs(name, out2)
