"""SQL smoke tests: the backbone harness.

Mirror of the reference's arroyo-sql-testing suite (SURVEY §4.1,
smoke_tests.rs:33-436): every query in tests/smoke/queries runs three ways —
(a) to completion at parallelism 1;
(b) at parallelism 2 with checkpoints at epochs 1-3, stopping at epoch 3;
(c) restored from epoch 3 at parallelism 3, run to completion —
and the output is diffed (order-insensitive; updating streams are
debezium-merged first) against golden files produced by independent oracles
(tests/smoke/generate.py).
"""

from __future__ import annotations

import glob
import json
import os

import pytest

SMOKE = os.path.join(os.path.dirname(__file__), "smoke")
QUERIES = sorted(
    os.path.splitext(os.path.basename(p))[0]
    for p in glob.glob(os.path.join(SMOKE, "queries", "*.sql"))
)


def load_sql(name: str, output_path: str) -> str:
    with open(os.path.join(SMOKE, "queries", f"{name}.sql")) as f:
        sql = f.read()
    return sql.replace("$input_dir", os.path.join(SMOKE, "inputs")).replace(
        "$output_path", output_path
    )


def is_updating(name: str) -> bool:
    import sys

    sys.path.insert(0, SMOKE)
    try:
        from generate import UPDATING  # type: ignore

        return name in UPDATING
    finally:
        sys.path.pop(0)


def canon(row: dict) -> str:
    """Canonical form for order-insensitive multiset comparison; floats
    rounded so summation order doesn't flip the diff."""
    out = {}
    for k, v in sorted(row.items()):
        if isinstance(v, float):
            v = round(v, 6)
        out[k] = v
    return json.dumps(out, sort_keys=True)


def merge_debezium(lines: list[dict]) -> list[dict]:
    """Apply retract/append envelopes to a multiset (reference
    smoke_tests.rs:475-521 merge_debezium)."""
    counts: dict[str, int] = {}
    rows: dict[str, dict] = {}
    for obj in lines:
        if "op" not in obj:
            key = canon(obj)
            counts[key] = counts.get(key, 0) + 1
            rows[key] = obj
            continue
        if obj["op"] in ("c", "r"):
            row = obj["after"]
            key = canon(row)
            counts[key] = counts.get(key, 0) + 1
            rows[key] = row
        elif obj["op"] == "d":
            row = obj["before"]
            key = canon(row)
            if key not in counts:
                raise AssertionError(f"retract of unseen row: {row}")
            counts[key] -= 1
            if counts[key] == 0:
                del counts[key]
        elif obj["op"] == "u":
            bkey = canon(obj["before"])
            counts[bkey] = counts.get(bkey, 0) - 1
            if counts.get(bkey) == 0:
                del counts[bkey]
            row = obj["after"]
            key = canon(row)
            counts[key] = counts.get(key, 0) + 1
            rows[key] = row
    out = []
    for key, n in counts.items():
        out.extend([rows[key]] * n)
    return out


def read_output(path: str) -> list[dict]:
    lines: list[dict] = []
    for p in sorted(glob.glob(path) + glob.glob(path + ".*")):
        with open(p) as f:
            for line in f:
                if line.strip():
                    lines.append(json.loads(line))
    return lines


def assert_outputs(name: str, output_path: str):
    golden_path = os.path.join(SMOKE, "golden", f"{name}.json")
    with open(golden_path) as f:
        golden = [json.loads(l) for l in f if l.strip()]
    got = read_output(output_path)
    if is_updating(name):
        got = merge_debezium(got)
    got_c = sorted(canon(r) for r in got)
    want_c = sorted(canon(r) for r in golden)
    assert got_c == want_c, (
        f"{name}: output mismatch ({len(got_c)} rows vs {len(want_c)} golden)\n"
        f"extra:   {[r for r in got_c if r not in want_c][:5]}\n"
        f"missing: {[r for r in want_c if r not in got_c][:5]}"
    )


def build(sql: str, parallelism: int, job_id: str, restore_epoch=None):
    import sys

    sys.path.insert(0, SMOKE)
    try:
        import udfs  # noqa: F401  (registers the suite's test UDAFs)
    finally:
        sys.path.pop(0)
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.sql import plan_query
    from arroyo_tpu.sql.planner import set_parallelism

    pp = plan_query(sql)
    if parallelism > 1:
        set_parallelism(pp.graph, parallelism)
    eng = Engine(pp.graph, job_id=job_id, restore_epoch=restore_epoch)
    return eng


@pytest.mark.parametrize("chaining", [False, True], ids=["unchained", "chained"])
@pytest.mark.parametrize("name", QUERIES)
def test_smoke(name, chaining, tmp_path, _storage):
    from arroyo_tpu import config as cfg

    cfg.update({"pipeline.chaining.enabled": chaining})

    # ---- run 1: parallelism 1, to completion --------------------------
    out1 = str(tmp_path / "out1.json")
    eng = build(load_sql(name, out1), 1, f"{name}-p1")
    eng.run_to_completion(timeout=180)
    assert_outputs(name, out1)

    # ---- run 2: parallelism 2, checkpoints 1-3, stop at 3 -------------
    # the source gate holds every source mid-file until 3 barriers have
    # passed, so the mid-stream stop is deterministic (never silently
    # degrades to a completed run; reference smoke_tests.rs:300-356)
    out2 = str(tmp_path / "out2.json")
    sql2 = load_sql(name, out2)
    cfg.update({"testing.source-gate-epochs": 3})
    try:
        eng2 = build(sql2, 2, f"{name}-ckpt")
        eng2.start()
        for epoch in (1, 2):
            assert eng2.checkpoint_and_wait(epoch, timeout=60), (
                f"checkpoint epoch {epoch} did not complete mid-stream"
            )
            if epoch == 2:
                # reference runs state compaction after epoch 2
                eng2.compact(2)
        assert eng2.checkpoint_and_wait(3, timeout=60, then_stop=True), (
            "checkpoint epoch 3 (stopping) did not complete mid-stream"
        )
        eng2.join(timeout=120)
    finally:
        cfg.update({"testing.source-gate-epochs": 0})

    # ---- run 3: restore from epoch 3 at parallelism 3, finish ---------
    # compact the restore epoch + GC older epochs first: restore must
    # work from compacted generation-1 files alone
    eng2.compact(3)
    eng2.cleanup(min_epoch=3)
    eng3 = build(sql2, 3, f"{name}-ckpt", restore_epoch=3)
    eng3.run_to_completion(timeout=180)
    assert_outputs(name, out2)
