"""Static-analysis subsystem tests (arroyo_tpu.analysis).

Three layers:
- plan-analyzer rules: one minimal positive and one negative graph/SQL
  fixture per rule, plus the known-bad pipeline catalog
  (tests/smoke/queries_bad) asserting each file's annotated rule id;
- repo lint rules: AST fixtures per rule + waiver semantics, and the
  gate that this repository itself lints clean;
- determinism: same input -> identical ordered diagnostics.
"""

from __future__ import annotations

import glob
import os
import re

import pytest

import arroyo_tpu
from arroyo_tpu.analysis import (
    AnalysisError,
    Severity,
    analyze_graph,
    check_sql,
    lint_paths,
    lint_source,
)
from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
from arroyo_tpu.expr import Col
from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

SMOKE = os.path.join(os.path.dirname(__file__), "smoke")
BAD_DIR = os.path.join(SMOKE, "queries_bad")
PKG_DIR = os.path.dirname(os.path.abspath(arroyo_tpu.__file__))


def load_bad(path: str) -> tuple[str, str, str]:
    """-> (sql, mode, rule_id) from a queries_bad file's annotation."""
    with open(path) as f:
        text = f.read()
    m = re.match(r"--\s*(reject|warn):\s*(\S+)", text)
    assert m, f"{path} lacks a '-- reject:/-- warn: <rule>' annotation"
    sql = text.replace("$input_dir", os.path.join(SMOKE, "inputs")).replace(
        "$output_path", "/tmp/qb_out.json")
    return sql, m.group(1), m.group(2)


BAD_FILES = sorted(glob.glob(os.path.join(BAD_DIR, "*.sql")))


def ids_of(diags):
    return {d.rule_id for d in diags}


# ---------------------------------------------------------------- graph kit


def schema(*cols: tuple[str, str], has_keys: bool = False) -> Schema:
    return Schema.of(list(cols) + [(TIMESTAMP_FIELD, "int64")],
                     has_keys=has_keys)


def base_graph(connector: str = "single_file", fmt: str = "json") -> tuple[Graph, Schema]:
    g = Graph()
    s = schema(("a", "int64"), ("b", "int64"))
    g.add_node(Node("src_0", OpName.SOURCE,
                    {"connector": connector, "format": fmt, "schema": s,
                     "path": "/dev/null"}, 1))
    return g, s


def add_sink(g: Graph, src: str, s: Schema, fmt: str = "json") -> None:
    g.add_node(Node("sink_0", OpName.SINK,
                    {"connector": "single_file", "format": fmt, "schema": s,
                     "path": "/tmp/out"}, 1))
    g.add_edge(src, "sink_0", EdgeType.FORWARD, s)


def errors(diags):
    return [d for d in diags if d.severity == Severity.ERROR]


# ------------------------------------------------------------ catalog tests


def _register_smoke_fixtures():
    """Import the smoke suite's UDF/connector fixtures (idempotent): the
    AR008 catalog entry plans a deliberately-broken test connector that
    tests/smoke/udfs.py registers."""
    import sys
    sys.path.insert(0, SMOKE)
    try:
        import udfs  # noqa: F401
    finally:
        sys.path.pop(0)


@pytest.mark.parametrize("path", BAD_FILES, ids=[os.path.basename(p)[:-4] for p in BAD_FILES])
def test_known_bad_catalog(path):
    """Every cataloged bad pipeline produces exactly its annotated
    diagnostic: 'reject' entries fail `check` with that rule id as an
    ERROR, 'warn' entries plan successfully but carry the warning."""
    _register_smoke_fixtures()
    sql, mode, rule = load_bad(path)
    pp, diags = check_sql(sql)
    if mode == "reject":
        errs = errors(diags)
        assert errs, f"{path}: expected rejection, got {diags}"
        assert rule in ids_of(errs), f"{path}: expected {rule}, got {ids_of(errs)}"
    else:
        assert pp is not None and not errors(diags), f"{path}: unexpectedly rejected: {diags}"
        assert rule in ids_of(diags), f"{path}: expected warning {rule}, got {ids_of(diags)}"


def test_all_smoke_families_accepted():
    """The analyzer must not reject any golden-output family."""
    from arroyo_tpu.sql import plan_query

    _register_smoke_fixtures()
    for p in sorted(glob.glob(os.path.join(SMOKE, "queries", "*.sql"))):
        sql = open(p).read().replace("$input_dir", os.path.join(SMOKE, "inputs")) \
            .replace("$output_path", "/tmp/qa_out.json")
        plan_query(sql)  # analyze=True: raises AnalysisError on any ERROR


def test_unaligned_hop_raises_at_plan_time():
    """The satellite guarantee: plan_query (the path every execution
    surface uses) rejects unaligned hop() before anything runs."""
    sql, _mode, rule = load_bad(
        os.path.join(BAD_DIR, "most_active_driver_last_hour_unaligned.sql"))
    from arroyo_tpu.sql import plan_query

    with pytest.raises(AnalysisError) as ei:
        plan_query(sql)
    assert rule in str(ei.value)
    assert ei.value.diagnostics[0].rule_id == rule


# ----------------------------------------------------- plan rules, per-rule


def test_ar001_edge_schema():
    g, s = base_graph()
    g.add_node(Node("value_1", OpName.VALUE,
                    {"projections": [("x", Col("missing"))]}, 1))
    g.add_edge("src_0", "value_1", EdgeType.FORWARD, s)
    add_sink(g, "value_1", schema(("x", "int64")))
    diags = analyze_graph(g)
    assert "AR001" in ids_of(errors(diags))

    g2, s2 = base_graph()
    g2.add_node(Node("value_1", OpName.VALUE,
                     {"projections": [("x", Col("a"))]}, 1))
    g2.add_edge("src_0", "value_1", EdgeType.FORWARD, s2)
    add_sink(g2, "value_1", schema(("x", "int64")))
    assert "AR001" not in ids_of(analyze_graph(g2))


def test_ar001_unnest_column():
    g, s = base_graph()
    g.add_node(Node("unnest_1", OpName.UNNEST,
                    {"column": "gone", "out_name": "v", "out_dtype": "int64"}, 1))
    g.add_edge("src_0", "unnest_1", EdgeType.FORWARD, s)
    add_sink(g, "unnest_1", schema(("v", "int64")))
    assert "AR001" in ids_of(errors(analyze_graph(g)))

    g2, s2 = base_graph()
    g2.add_node(Node("unnest_1", OpName.UNNEST,
                     {"column": "a", "out_name": "v", "out_dtype": "int64"}, 1))
    g2.add_edge("src_0", "unnest_1", EdgeType.FORWARD, s2)
    add_sink(g2, "unnest_1", schema(("v", "int64")))
    assert "AR001" not in ids_of(analyze_graph(g2))


def _sliding_graph(width_us: int, slide_us: int) -> Graph:
    g, s = base_graph()
    g.add_node(Node("agg_1", OpName.SLIDING_AGGREGATE,
                    {"key_fields": [], "aggregates": [("c", "count", None)],
                     "width_micros": width_us, "slide_micros": slide_us}, 1))
    g.add_edge("src_0", "agg_1", EdgeType.FORWARD, s)
    add_sink(g, "agg_1", schema(("c", "int64")))
    return g


def test_ar002_unaligned_hop():
    diags = analyze_graph(_sliding_graph(10_000_000, 3_000_000))
    hits = [d for d in errors(diags) if d.rule_id == "AR002"]
    assert hits and "slide" in hits[0].message and hits[0].hint
    assert "AR002" not in ids_of(analyze_graph(_sliding_graph(10_000_000, 2_000_000)))


def test_ar003_updating_into_window():
    g, s = base_graph(fmt="debezium_json")
    g.add_node(Node("agg_1", OpName.TUMBLING_AGGREGATE,
                    {"key_fields": [], "aggregates": [("c", "count", None)],
                     "width_micros": 1_000_000}, 1))
    g.add_edge("src_0", "agg_1", EdgeType.FORWARD, s)
    add_sink(g, "agg_1", schema(("c", "int64")))
    assert "AR003" in ids_of(errors(analyze_graph(g)))

    g2, s2 = base_graph(fmt="json")
    g2.add_node(Node("agg_1", OpName.TUMBLING_AGGREGATE,
                     {"key_fields": [], "aggregates": [("c", "count", None)],
                      "width_micros": 1_000_000}, 1))
    g2.add_edge("src_0", "agg_1", EdgeType.FORWARD, s2)
    add_sink(g2, "agg_1", schema(("c", "int64")))
    assert "AR003" not in ids_of(analyze_graph(g2))


def _updating_agg_graph(connector: str, ttl: int = 0) -> Graph:
    g, s = base_graph(connector=connector)
    cfg = {"key_fields": [], "aggregates": [("c", "count", None)]}
    if ttl:
        cfg["ttl_micros"] = ttl
    g.add_node(Node("agg_1", OpName.UPDATING_AGGREGATE, cfg, 1))
    g.add_edge("src_0", "agg_1", EdgeType.FORWARD, s)
    add_sink(g, "agg_1", schema(("c", "int64")), fmt="debezium_json")
    return g


def test_ar004_unbounded_state():
    assert "AR004" in ids_of(analyze_graph(_updating_agg_graph("kafka")))
    # a TTL bounds the state; a bounded source bounds it too
    assert "AR004" not in ids_of(analyze_graph(_updating_agg_graph("kafka", ttl=60_000_000)))
    assert "AR004" not in ids_of(analyze_graph(_updating_agg_graph("single_file")))


def test_ar005_retraction_sink():
    g, s = base_graph()
    g.add_node(Node("agg_1", OpName.UPDATING_AGGREGATE,
                    {"key_fields": [], "aggregates": [("c", "count", None)]}, 1))
    g.add_edge("src_0", "agg_1", EdgeType.FORWARD, s)
    add_sink(g, "agg_1", schema(("c", "int64")), fmt="json")
    diags = analyze_graph(g)
    hit = [d for d in diags if d.rule_id == "AR005"]
    assert hit and hit[0].severity == Severity.WARNING

    g2, s2 = base_graph()
    g2.add_node(Node("agg_1", OpName.UPDATING_AGGREGATE,
                     {"key_fields": [], "aggregates": [("c", "count", None)]}, 1))
    g2.add_edge("src_0", "agg_1", EdgeType.FORWARD, s2)
    add_sink(g2, "agg_1", schema(("c", "int64")), fmt="debezium_json")
    assert "AR005" not in ids_of(analyze_graph(g2))


def test_ar006_barrier_reachability():
    # orphan operator: no input edges -> barriers can never reach it
    g, s = base_graph()
    add_sink(g, "src_0", s)
    g.add_node(Node("agg_orphan", OpName.TUMBLING_AGGREGATE,
                    {"key_fields": [], "aggregates": [],
                     "width_micros": 1_000_000}, 1))
    hits = [d for d in errors(analyze_graph(g)) if d.rule_id == "AR006"]
    assert hits and hits[0].site == "agg_orphan"

    # dead source: output never reaches a sink -> warning
    g2, s2 = base_graph()
    add_sink(g2, "src_0", s2)
    g2.add_node(Node("src_dead", OpName.SOURCE,
                     {"connector": "single_file", "schema": s2,
                      "path": "/dev/null"}, 1))
    diags = analyze_graph(g2)
    hits = [d for d in diags if d.rule_id == "AR006"]
    assert hits and hits[0].severity == Severity.WARNING and hits[0].site == "src_dead"

    g3, s3 = base_graph()
    add_sink(g3, "src_0", s3)
    assert "AR006" not in ids_of(analyze_graph(g3))


def _shuffle_graph(key_names: list[str], group_by: list[str],
                   with_key_node: bool = True) -> Graph:
    g, s = base_graph()
    ks = schema(("a", "int64"), ("b", "int64"), has_keys=True)
    up = "src_0"
    if with_key_node:
        g.add_node(Node("key_1", OpName.KEY,
                        {"keys": [(n, Col(n)) for n in key_names]}, 1))
        g.add_edge("src_0", "key_1", EdgeType.FORWARD, s)
        up = "key_1"
    g.add_node(Node("agg_1", OpName.UPDATING_AGGREGATE,
                    {"key_fields": group_by,
                     "aggregates": [("c", "count", None)]}, 2))
    g.add_edge(up, "agg_1", EdgeType.SHUFFLE, ks if with_key_node else s)
    add_sink(g, "agg_1", schema(("c", "int64")), fmt="debezium_json")
    return g


def test_ar007_shuffle_keys():
    assert "AR007" not in ids_of(analyze_graph(_shuffle_graph(["a"], ["a"])))
    # keyed by the wrong column
    diags = analyze_graph(_shuffle_graph(["b"], ["a"]))
    assert "AR007" in ids_of(errors(diags))
    # no key calculation upstream at all
    diags = analyze_graph(_shuffle_graph([], ["a"], with_key_node=False))
    hits = [d for d in errors(diags) if d.rule_id == "AR007"]
    assert hits and "no upstream key calculation" in hits[0].message


# ----------------------------------------------------------- lint, per-rule


def test_lr101_adhoc_retry_sleep():
    bad = (
        "import time\n"
        "def f():\n"
        "    while True:\n"
        "        try:\n"
        "            io()\n"
        "        except OSError:\n"
        "            time.sleep(1.0)\n"
    )
    diags = lint_source(bad, "arroyo_tpu/connectors/x.py")
    assert "LR101" in ids_of(diags)
    good = bad.replace("time.sleep(1.0)", "time.sleep(backoff.next_delay())")
    assert "LR101" not in ids_of(lint_source(good, "arroyo_tpu/connectors/x.py"))
    # the shared layer itself is allowed to sleep
    assert "LR101" not in ids_of(lint_source(bad, "arroyo_tpu/utils/retry.py"))


def test_lr102_swallowed_exception():
    bare = "def f():\n    try:\n        g()\n    except:\n        pass\n"
    assert "LR102" in ids_of(lint_source(bare, "arroyo_tpu/api/x.py"))
    swallowed = "def f():\n    try:\n        g()\n    except Exception:\n        pass\n"
    assert "LR102" in ids_of(lint_source(swallowed, "arroyo_tpu/engine/x.py"))
    # outside the strict layers a broad except-pass is tolerated
    assert "LR102" not in ids_of(lint_source(swallowed, "arroyo_tpu/api/x.py"))
    logged = swallowed.replace("pass", "log.warning('x')")
    assert "LR102" not in ids_of(lint_source(logged, "arroyo_tpu/engine/x.py"))


def test_lr103_unseeded_random():
    bad = "import random\ndef f():\n    return random.uniform(0, 1)\n"
    assert "LR103" in ids_of(lint_source(bad, "arroyo_tpu/operators/x.py"))
    assert "LR103" in ids_of(lint_source(
        "import numpy as np\ndef f():\n    return np.random.rand(4)\n",
        "arroyo_tpu/engine/x.py"))
    # out of scope (e.g. retry jitter) and seeded instances are fine
    assert "LR103" not in ids_of(lint_source(bad, "arroyo_tpu/utils/x.py"))
    seeded = "import random\ndef f(seed):\n    return random.Random(seed).uniform(0, 1)\n"
    assert "LR103" not in ids_of(lint_source(seeded, "arroyo_tpu/operators/x.py"))


def test_lr104_host_sync_hot_path():
    bad = (
        "import jax.numpy as jnp\nimport numpy as np\n"
        "class Op:\n"
        "    def process_batch(self, batch, ctx, collector):\n"
        "        v = jnp.sum(batch.col)\n"
        "        return float(v)\n"
    )
    diags = lint_source(bad, "arroyo_tpu/operators/x.py")
    assert "LR104" in ids_of(diags)
    assert "LR104" in ids_of(lint_source(
        bad.replace("float(v)", "np.asarray(v)"), "arroyo_tpu/operators/x.py"))
    assert "LR104" in ids_of(lint_source(
        "def flush(x):\n    x.block_until_ready()\n", "arroyo_tpu/ops/x.py"))
    # host-side numpy on host values is the normal case — not flagged
    host = (
        "import numpy as np\n"
        "class Op:\n"
        "    def process_batch(self, batch, ctx, collector):\n"
        "        v = batch.col\n"
        "        return np.asarray(v)\n"
    )
    assert "LR104" not in ids_of(lint_source(host, "arroyo_tpu/operators/x.py"))


def test_lr105_folded_into_lr403():
    """LR105 is retired as a standalone rule: its intraprocedural shape
    now fires as LR403 from the concurrency auditor (which lint_paths
    runs alongside these rules); the old id survives only as a waiver
    alias. See tests/test_concurrency_audit.py for the LR403 fixtures."""
    from arroyo_tpu.analysis import CONCURRENCY_RULES
    from arroyo_tpu.analysis.concurrency_audit import (
        audit_concurrency_source,
    )
    from arroyo_tpu.analysis.repo_lint import RULES

    assert "LR105" not in {rid for rid, _sev, _fn in RULES}
    assert "LR403" in CONCURRENCY_RULES
    bad = (
        "import time\n"
        "def f(self):\n"
        "    with self._lock:\n"
        "        time.sleep(1)\n"
    )
    assert "LR403" in {d.rule_id for d in audit_concurrency_source(
        bad, "arroyo_tpu/engine/x.py")}
    # os.path.join / "".join under a lock are not thread joins
    path = (
        "import os\n"
        "def f(self):\n"
        "    with self._lock:\n"
        "        return os.path.join('a', 'b')\n"
    )
    assert "LR403" not in {d.rule_id for d in audit_concurrency_source(
        path, "arroyo_tpu/engine/x.py")}


def test_lr106_fault_site_coverage():
    uncovered = (
        "def write_bytes(path, data):\n"
        "    open(path, 'wb').write(data)\n"
    )
    assert "LR106" in ids_of(lint_source(uncovered, "arroyo_tpu/state/storage.py"))
    covered = (
        "from ..faults import fault_point\n"
        "def _guarded(site, key, fn):\n"
        "    fault_point(site, key=key)\n"
        "    return fn()\n"
        "def write_bytes(path, data):\n"
        "    _guarded('storage.put', path, lambda: None)\n"
    )
    assert "LR106" not in ids_of(lint_source(covered, "arroyo_tpu/state/storage.py"))
    # rule only binds to declared fault-boundary modules
    assert "LR106" not in ids_of(lint_source(uncovered, "arroyo_tpu/utils/x.py"))


def test_lr107_emit_in_loop():
    bad = (
        "def on_close(self, ctx, collector):\n"
        "    for w in self.windows:\n"
        "        collector.collect(self.window_batch(w))\n"
    )
    assert "LR107" in ids_of(lint_source(bad, "arroyo_tpu/operators/x.py"))
    assert "LR107" in ids_of(lint_source(bad, "arroyo_tpu/windows/x.py"))
    # connectors are out of scope: a source's poll loop IS its emit contract
    assert "LR107" not in ids_of(lint_source(bad, "arroyo_tpu/connectors/x.py"))
    fused = (
        "def on_close(self, ctx, collector):\n"
        "    parts = [self.window_cols(w) for w in self.windows]\n"
        "    collector.collect(concat(parts))\n"
    )
    assert "LR107" not in ids_of(lint_source(fused, "arroyo_tpu/operators/x.py"))
    waived = bad.replace(
        "collector.collect(self.window_batch(w))",
        "collector.collect(self.window_batch(w))  "
        "# lint: waive LR107 — windows carry incompatible schemas")
    assert "LR107" not in ids_of(lint_source(waived, "arroyo_tpu/operators/x.py"))


def test_lr108_bare_print():
    bad = (
        "def poll(self):\n"
        "    print('got batch', 42)\n"
    )
    # library code: worker stdout is the JSON-lines control protocol
    assert "LR108" in ids_of(lint_source(bad, "arroyo_tpu/engine/x.py"))
    assert "LR108" in ids_of(lint_source(bad, "arroyo_tpu/connectors/x.py"))
    # CLI entry points own their stdout; bench/tools live outside the package
    assert "LR108" not in ids_of(lint_source(bad, "arroyo_tpu/cli.py"))
    assert "LR108" not in ids_of(lint_source(bad, "arroyo_tpu/__main__.py"))
    assert "LR108" not in ids_of(lint_source(bad, "tools/profile.py"))
    assert "LR108" not in ids_of(lint_source(bad, "bench.py"))
    logged = (
        "import logging\n"
        "def poll(self):\n"
        "    logging.getLogger('arroyo_tpu.engine').info('got batch %d', 42)\n"
    )
    assert "LR108" not in ids_of(lint_source(logged, "arroyo_tpu/engine/x.py"))
    waived = bad.replace(
        "print('got batch', 42)",
        "print('got batch', 42)  # lint: waive LR108 — CLI-owned output")
    assert "LR108" not in ids_of(lint_source(waived, "arroyo_tpu/engine/x.py"))


def test_lr109_adhoc_self_timing():
    bad = (
        "import time\n"
        "def process_batch(self, batch, ctx, collector, input_index=0):\n"
        "    t0 = time.perf_counter()\n"
        "    work(batch)\n"
        "    self.total += time.time() - t0\n"
    )
    # self-measurement in operator/window/state code fragments attribution
    for rel in ("arroyo_tpu/operators/x.py", "arroyo_tpu/windows/x.py",
                "arroyo_tpu/state/x.py", "arroyo_tpu/ops/x.py"):
        assert "LR109" in ids_of(lint_source(bad, rel)), rel
    # the engine/profiler layers OWN the stopwatch; connectors poll clocks
    assert "LR109" not in ids_of(lint_source(bad, "arroyo_tpu/engine/x.py"))
    assert "LR109" not in ids_of(lint_source(bad, "arroyo_tpu/obs/profile.py"))
    assert "LR109" not in ids_of(lint_source(bad, "arroyo_tpu/connectors/x.py"))
    # time.sleep is not a clock read (LR101/LR105 cover sleeps)
    sleepy = "import time\ndef handle_tick(self, ctx, c):\n    time.sleep(0.1)\n"
    assert "LR109" not in ids_of(lint_source(sleepy, "arroyo_tpu/operators/x.py"))
    # a justified waiver records WHY a clock read is not self-measurement
    waived = bad.replace(
        "t0 = time.perf_counter()",
        "t0 = time.perf_counter()  # lint: waive LR109 — cache TTL wall clock"
    ).replace(
        "self.total += time.time() - t0",
        "self.total += time.time() - t0  # lint: waive LR109 — cache TTL wall clock")
    assert "LR109" not in ids_of(lint_source(waived, "arroyo_tpu/operators/x.py"))


def test_lr110_logger_in_function():
    bad = (
        "import logging\n"
        "def handle(self):\n"
        "    logging.getLogger('arroyo_tpu.x').warning('boom')\n"
    )
    # per-call named-logger acquisition anywhere in the package
    assert "LR110" in ids_of(lint_source(bad, "arroyo_tpu/controller/x.py"))
    assert "LR110" in ids_of(lint_source(bad, "arroyo_tpu/engine/x.py"))
    # module-level acquisition is the convention — never flagged
    good = (
        "import logging\n"
        "_log = logging.getLogger('arroyo_tpu.x')\n"
        "def handle(self):\n"
        "    _log.warning('boom')\n"
    )
    assert "LR110" not in ids_of(lint_source(good, "arroyo_tpu/controller/x.py"))
    # the bare root logger (logging-INIT code reconfiguring handlers) is exempt
    root = (
        "import logging\n"
        "def init():\n"
        "    logging.getLogger().setLevel(logging.INFO)\n"
    )
    assert "LR110" not in ids_of(lint_source(root, "arroyo_tpu/server_common.py"))
    # outside the package (tools, tests) the rule does not apply
    assert "LR110" not in ids_of(lint_source(bad, "tools/x.py"))
    waived = bad.replace(
        "logging.getLogger('arroyo_tpu.x').warning('boom')",
        "logging.getLogger('arroyo_tpu.x').warning('boom')"
        "  # lint: waive LR110 — dynamic per-job logger name")
    assert "LR110" not in ids_of(lint_source(waived, "arroyo_tpu/controller/x.py"))


def test_lr111_jit_in_hot_path():
    bad = (
        "import jax\n"
        "class Op:\n"
        "    def process_batch(self, batch, ctx, collector, input_index=0):\n"
        "        fn = jax.jit(lambda x: x + 1)\n"
        "        collector.collect(fn(batch))\n"
    )
    # per-batch jit in any operator hot-path method is the retrace bug
    for rel in ("arroyo_tpu/operators/x.py", "arroyo_tpu/windows/x.py",
                "arroyo_tpu/ops/x.py"):
        assert "LR111" in ids_of(lint_source(bad, rel)), rel
    for hot in ("handle_watermark", "handle_tick"):
        variant = bad.replace("process_batch", hot)
        assert "LR111" in ids_of(
            lint_source(variant, "arroyo_tpu/operators/x.py")), hot
    # bare jit()/pjit() names count too (from-imports)
    frm = (
        "from jax import jit\n"
        "class Op:\n"
        "    def process_batch(self, b, ctx, collector, input_index=0):\n"
        "        jit(lambda x: x)(b)\n"
    )
    assert "LR111" in ids_of(lint_source(frm, "arroyo_tpu/windows/x.py"))
    # jit in a once-per-config builder (not a hot-path method) is the
    # sanctioned pattern — slot_agg's _build_slot_jax shape
    good = (
        "import jax\n"
        "def _build(cfg):\n"
        "    return jax.jit(lambda x: x + 1)\n"
        "class Op:\n"
        "    def process_batch(self, b, ctx, collector, input_index=0):\n"
        "        self._fn(b)\n"
    )
    assert "LR111" not in ids_of(lint_source(good, "arroyo_tpu/ops/x.py"))
    # outside operator/window/ops dirs the segment compiler owns jit use
    assert "LR111" not in ids_of(lint_source(bad, "arroyo_tpu/engine/x.py"))
    waived = bad.replace(
        "fn = jax.jit(lambda x: x + 1)",
        "fn = jax.jit(lambda x: x + 1)  # lint: waive LR111 — test fixture")
    assert "LR111" not in ids_of(lint_source(waived, "arroyo_tpu/operators/x.py"))
    # the repo itself must hold the invariant
    from arroyo_tpu.analysis import lint_paths

    assert not [d for d in lint_paths(["arroyo_tpu"])
                if d.rule_id == "LR111"]


def test_waivers():
    bad = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # lint: waive LR102 — probe failure is expected here\n"
        "        pass\n"
    )
    assert ids_of(lint_source(bad, "arroyo_tpu/engine/x.py")) == set()
    # a waiver without justification does not suppress
    nojust = bad.replace(" — probe failure is expected here", "")
    assert "LR102" in ids_of(lint_source(nojust, "arroyo_tpu/engine/x.py"))
    # a waiver for a different rule does not suppress
    wrong = bad.replace("LR102", "LR105")
    assert "LR102" in ids_of(lint_source(wrong, "arroyo_tpu/engine/x.py"))


# --------------------------------------------------------------- CI gates


def test_lint_fault_sites_in_sync():
    """The linter's literal site list must track faults.SITES exactly."""
    from arroyo_tpu import faults
    from arroyo_tpu.analysis.repo_lint import _DECLARED_FAULT_SITES

    assert set(_DECLARED_FAULT_SITES) == set(faults.SITES)


def test_repo_lints_clean():
    """The CI gate: zero unwaived findings over the whole package."""
    diags = lint_paths([PKG_DIR], root=os.path.dirname(PKG_DIR))
    assert diags == [], "repo lint found:\n" + "\n".join(d.render() for d in diags)


def test_cli_check_and_lint():
    from arroyo_tpu.cli import main

    bad = os.path.join(BAD_DIR, "unaligned_hop_group_by.sql")
    good = os.path.join(SMOKE, "queries", "select_star.sql")
    # catalog files use harness placeholders; materialize a checkable copy
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        for src, name in ((bad, "bad.sql"), (good, "good.sql")):
            sql = open(src).read().replace("$input_dir", os.path.join(SMOKE, "inputs")) \
                .replace("$output_path", os.path.join(td, "out.json"))
            with open(os.path.join(td, name), "w") as f:
                f.write(sql)
        assert main(["check", os.path.join(td, "bad.sql")]) == 1
        assert main(["check", os.path.join(td, "good.sql")]) == 0
    assert main(["lint", PKG_DIR]) == 0


# ------------------------------------------------------------- determinism


def test_determinism_plan_and_lint():
    """Same input -> byte-identical ordered diagnostics, repeatedly."""
    g = _sliding_graph(10_000_000, 3_000_000)
    # add more findings so ordering is actually exercised
    g.add_node(Node("agg_orphan", OpName.TUMBLING_AGGREGATE,
                    {"key_fields": [], "aggregates": [],
                     "width_micros": 1_000_000}, 1))
    runs = [analyze_graph(g) for _ in range(3)]
    assert runs[0] == runs[1] == runs[2]
    assert len(runs[0]) >= 2
    assert [d.sort_key() for d in runs[0]] == sorted(d.sort_key() for d in runs[0])

    sql, _m, _r = load_bad(os.path.join(BAD_DIR, "dead_memory_branch.sql"))
    d1 = check_sql(sql)[1]
    d2 = check_sql(sql)[1]
    assert d1 == d2 and d1

    src = open(os.path.join(PKG_DIR, "engine", "engine.py")).read()
    assert lint_source(src, "arroyo_tpu/engine/engine.py") == \
        lint_source(src, "arroyo_tpu/engine/engine.py")
