"""Observability plane: epoch-lifecycle tracing, event-time health metrics,
and the controller-side live job view (ISSUE 6).

Covers: the trace recorder capturing a full checkpoint span tree and its
Chrome trace-event export; timeout/wedge diagnostics naming the exact stuck
subtask; the overflow-clamped histogram quantiles; watermark-lag and
sink-latency metrics reaching the prometheus exposition and the per-second
controller snapshot; multi-worker snapshot merging; and the `top`/`trace`
CLIs reading everything back from the controller DB.
"""

from __future__ import annotations

import json
import os
import time

import pytest

import arroyo_tpu
from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
from arroyo_tpu.expr import Col
from arroyo_tpu.graph import EdgeType, Graph, Node, OpName
from arroyo_tpu.metrics import (
    Histogram,
    merge_job_metrics,
    registry,
)
from arroyo_tpu.obs import trace as obs_trace

SMOKE = os.path.join(os.path.dirname(__file__), "smoke")


# ---------------------------------------------------------------- histograms


def test_histogram_quantile_clamps_overflow():
    h = Histogram((1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 100.0, 200.0, 300.0):
        h.observe(v)
    # p99 lands in the +Inf bucket: clamped to the largest finite bound,
    # never inf (bench breakdown lines multiply by 1000 and must not print
    # 'infms'); the string form flags the clamp
    assert h.quantile(0.99) == 4.0
    assert h.quantile_str(0.99) == ">4.00"
    assert h.quantile_str(0.99, scale=1000, precision=1) == ">4000.0"
    # non-overflow quantiles are untouched
    assert h.quantile(0.2) == 1.0
    assert h.quantile_str(0.2) == "1.00"
    empty = Histogram((1.0,))
    assert empty.quantile(0.99) == 0.0
    assert empty.quantile_str(0.99) == "0.00"


def test_merge_job_metrics_unions_subtasks():
    def snap(sub, sent):
        return {"op": {"per_subtask": {sub: {
            "arroyo_worker_messages_sent": sent,
            "arroyo_worker_messages_recv": 0,
            "backpressure": 0.5 if sub == "1" else 0.1,
            "watermark_lag_seconds": 2.0 if sub == "1" else None,
            "queue_transit_p99_ms": 7.5,
        }}}}

    merged = merge_job_metrics([snap("0", 10), snap("1", 32)])
    m = merged["op"]
    assert set(m["per_subtask"]) == {"0", "1"}
    assert m["subtasks"] == 2
    assert m["arroyo_worker_messages_sent"] == 42
    assert m["backpressure"] == 0.5  # worst subtask wins
    assert m["watermark_lag_seconds"] == 2.0
    # identical snapshots (embedded worker sets share one registry) collapse
    # by label instead of double-counting
    again = merge_job_metrics([snap("0", 10), snap("0", 10)])
    assert again["op"]["arroyo_worker_messages_sent"] == 10


# ------------------------------------------------------------------- tracing


def _graph(tmp_path, n_rows=300, parallelism=1):
    src = tmp_path / "in.json"
    with open(src, "w") as f:
        for i in range(n_rows):
            f.write(json.dumps({"x": i, "_timestamp": i * 1000}) + "\n")
    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    rows: list = []
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "single_file", "path": str(src), "schema": S}, 1))
    g.add_node(Node("wm", OpName.WATERMARK, {
        "expr": Col(TIMESTAMP_FIELD), "interval_micros": 1000}, parallelism))
    g.add_node(Node("sink", OpName.SINK, {
        "connector": "vec", "rows": rows}, parallelism))
    g.add_edge("src", "wm",
               EdgeType.SHUFFLE if parallelism > 1 else EdgeType.FORWARD, S)
    g.add_edge("wm", "sink", EdgeType.FORWARD, S)
    return g, rows


def test_epoch_trace_lifecycle_and_chrome_export(tmp_path, _storage):
    from arroyo_tpu import config as cfg
    from arroyo_tpu.engine.engine import Engine

    cfg.update({"testing.source-read-delay-micros": 2000})
    g, rows = _graph(tmp_path)
    job = "trace-lifecycle"
    obs_trace.recorder.clear_job(job)
    eng = Engine(g, job_id=job)
    eng.start()
    assert eng.checkpoint_and_wait(1, timeout=60)
    eng.stop()
    eng.join(60)

    events = obs_trace.recorder.events(job, 1)
    kinds = {e["event"] for e in events}
    assert {"trigger", "align_start", "snapshot_start", "ack",
            "metadata_durable", "commit_delivered"} <= kinds
    # every task acked; the sink aligned before snapshotting
    acked = {(e["node"], e["subtask"]) for e in events if e["event"] == "ack"}
    assert acked == {("src", 0), ("wm", 0), ("sink", 0)}
    sink = {e["event"]: e["t_us"] for e in events if e["node"] == "sink"}
    assert sink["align_start"] <= sink["snapshot_start"] <= sink["ack"]

    phases = obs_trace.phase_durations(events)
    assert set(phases) == {"align", "snapshot", "ack", "commit"}
    assert all(v >= 0 for v in phases.values())
    assert obs_trace.dominant_phase(phases) in phases

    chrome = obs_trace.chrome_trace(job, {1: events})
    evs = chrome["traceEvents"]
    assert any(e["name"] == "epoch 1" and e["ph"] == "X" for e in evs)
    assert any(e["tid"] == "sink/0" and e["name"] == "snapshot" for e in evs)
    # complete epochs emit only closed spans / instants
    assert all(e["ph"] in ("X", "i") for e in evs)
    json.dumps(chrome)  # must be directly serializable for the API/CLI

    report = obs_trace.timeline_report(job, 1, events)
    assert "metadata_durable" in report and "dominant" in report


def test_checkpoint_timeout_report_names_stuck_subtask(tmp_path, _storage):
    """A dropped/held barrier (chaos `worker` hang fires after the snapshot
    is written, before the barrier is forwarded or acked) wedges the epoch;
    the CheckpointWait timeout attaches a trace timeline naming the exact
    stuck subtask and the downstream subtasks whose barrier never arrived."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.engine.engine import Engine

    cfg.update({"testing.source-read-delay-micros": 3000})
    g, rows = _graph(tmp_path, n_rows=2000)
    job = "trace-stuck"
    obs_trace.recorder.clear_job(job)
    faults.install("worker:hang=4@barrier=1&step=1", seed=3)
    eng = Engine(g, job_id=job)
    try:
        eng.start()
        wait = eng.checkpoint_and_wait(1, timeout=1.5)
        assert wait.outcome == "timeout"
        assert wait.missing  # the hung subtask never acked
        # the report names the hung subtask (snapshot written, never acked)
        # and/or the downstream ones still waiting on its barrier
        assert "stuck:" in wait.report
        assert ("never acked" in wait.report
                or "barrier never arrived" in wait.report
                or "still missing" in wait.report)
        stuck_names = [f"{n}/{s}" for n, s in wait.missing]
        assert any(name in wait.report for name in stuck_names)
        assert wait.report in repr(wait)  # chaos failures print this
    finally:
        faults.clear()
        eng.stop()
        eng.join(60)


# ----------------------------------------------------- event-time health


def test_watermark_lag_and_sink_latency_export(tmp_path, _storage):
    from arroyo_tpu.engine.engine import run_graph

    g, rows = _graph(tmp_path)
    job = "lag-metrics"
    registry.clear_job(job)
    run_graph(g, job_id=job, timeout=60)
    assert len(rows) > 0
    jm = registry.job_metrics(job)
    # the sink saw watermarks (lag = wall now - event time, input stamps
    # are micros near zero => huge positive lag) and observed per-batch
    # end-to-end latency
    assert jm["sink"]["watermark_lag_seconds"] > 0
    assert jm["sink"]["sink_event_latency_p99_s"] > 0
    assert jm["sink"]["per_subtask"]["0"]["watermark_lag_seconds"] > 0
    # non-terminal operators do not record sink latency
    assert jm["wm"]["sink_event_latency_p99_s"] is None
    text = registry.prometheus_text()
    assert f'arroyo_worker_watermark_lag_seconds{{job="{job}",operator="sink"' \
        in text
    assert f'arroyo_worker_sink_event_latency_seconds_count{{job="{job}"' \
        in text


def test_phase_histograms_export(_storage):
    registry.clear_job("phase-job")
    registry.observe_epoch_phases("phase-job", {
        "align": 0.2, "snapshot": 1.1, "ack": 0.01, "commit": 0.002})
    text = registry.prometheus_text()
    assert "# TYPE arroyo_checkpoint_phase_seconds histogram" in text
    assert 'arroyo_checkpoint_phase_seconds_count{job="phase-job",' \
        'phase="snapshot"} 1' in text
    registry.clear_job("phase-job")
    assert "phase-job" not in registry.prometheus_text()


# ------------------------------------------------- controller DB + CLIs


def _sql(tmp_path, name="grouped_aggregates"):
    with open(os.path.join(SMOKE, "queries", f"{name}.sql")) as f:
        sql = f.read()
    out = str(tmp_path / "out.json")
    # single_file sources read from subtask 0 only; at parallelism 2 the
    # other watermark subtask must declare itself Idle or the downstream
    # min-merge (correctly) holds the watermark until EOF and there is no
    # mid-run lag to observe
    sql = sql.replace(
        "event_time_field = 'timestamp'",
        "event_time_field = 'timestamp',\n  'idle-time-ms' = '300'")
    return sql.replace("$input_dir", os.path.join(SMOKE, "inputs")).replace(
        "$output_path", out), out


def test_top_and_trace_from_controller_db(tmp_path, _storage, capsys):
    """Acceptance: a live 2-worker job's controller DB carries nonzero
    watermark lag, throughput, and last-epoch phase durations; `top` and
    `trace` render them, and the API serves the Chrome trace."""
    from arroyo_tpu import cli
    from arroyo_tpu import config as cfg
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler

    sql, out = _sql(tmp_path)
    db_path = str(tmp_path / "ctl.db")
    db = Database(db_path)
    cfg.update({
        "controller.workers-per-job": 2,
        "checkpoint.interval-ms": 300,
        "testing.source-read-delay-micros": 15000,
    })
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    api = ApiServer(db, port=0).start()
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)

        # poll the LIVE job's DB snapshots until every event-time health
        # signal has been observed at least once: nonzero watermark lag,
        # nonzero out-rate, and a completed checkpoint carrying phase
        # durations (a terminal snapshot zeroes the windowed rates, so the
        # conditions accumulate across the run instead of being required
        # of one final sample)
        def _saw(s, key):
            return any((m.get(key) or 0) > 0
                       for m in (s or {}).values() if isinstance(m, dict))

        deadline = time.monotonic() + 90
        snap = ckpt_phases = None
        lag_seen = rate_seen = False
        while time.monotonic() < deadline:
            s = db.get_metrics(jid)
            if s:
                snap = s
            lag_seen = lag_seen or _saw(s, "watermark_lag_seconds")
            rate_seen = rate_seen or _saw(s, "messages_per_sec")
            if ckpt_phases is None:
                ckpt_phases = next(
                    (json.loads(c["phases"]) for c in db.list_checkpoints(jid)
                     if c["state"] == "complete" and c.get("phases")), None)
            if lag_seen and rate_seen and ckpt_phases:
                break
            if db.get_job(jid)["state"] != "Running":
                # drained: the final registry snapshot still carries lag
                s = db.get_metrics(jid)
                lag_seen = lag_seen or _saw(s, "watermark_lag_seconds")
                snap = s or snap
                break
            time.sleep(0.1)
        assert snap, "no metrics snapshot reached the controller DB"
        assert lag_seen, snap
        assert rate_seen, snap
        assert ckpt_phases and set(ckpt_phases) <= {
            "align", "snapshot", "ack", "commit"}, ckpt_phases

        # the live view renders from exactly that DB state
        assert cli.main(["top", jid, "--db", db_path, "--once"]) == 0
        frame = capsys.readouterr().out
        assert "operator" in frame and "wm lag" in frame
        assert "last epoch" in frame and "dominant" in frame

        # trace CLI: chrome export + human report
        assert cli.main(["trace", jid, "--db", db_path]) == 0
        chrome = json.loads(capsys.readouterr().out)
        assert chrome["traceEvents"]
        assert cli.main(["trace", jid, "--db", db_path, "--report"]) == 0
        report = capsys.readouterr().out
        assert "trace (" in report and "metadata_durable" in report

        # API endpoint serves the same trace
        import urllib.request

        with urllib.request.urlopen(
                f"http://127.0.0.1:{api.port}/api/v1/jobs/{jid}/traces",
                timeout=10) as r:
            payload = json.loads(r.read())
        assert payload["traceEvents"]

        ctl.wait_for_state(jid, "Finished", timeout=120)
        # terminal flush: every buffered epoch trace persisted to the DB
        assert db.list_traces(jid)
    finally:
        cfg.update({"controller.workers-per-job": 1,
                    "checkpoint.interval-ms": 10_000,
                    "testing.source-read-delay-micros": 0})
        ctl.stop()
        api.stop()


def test_top_header_renders_evolving_state():
    """`top` on an Evolving job says so (and flags the pending redeploy)
    instead of rendering a bare metrics-less frame."""
    from arroyo_tpu.obs.topview import render

    job = {"id": "j1", "state": "Evolving", "health": "ok", "n_workers": 1,
           "restarts": 0, "checkpoint_epoch": 3,
           "desired_query": "SELECT 1"}
    frame = render(job, None)
    assert "evolving" in frame and "redeploy pending" in frame
    # once the request is consumed the flag drops but the state still shows
    frame = render({**job, "desired_query": None}, None)
    assert "evolving" in frame and "redeploy pending" not in frame
