"""Object storage abstraction (state/storage.py): URL dispatch, S3 checkpoint
round trip against an in-memory fake client (reference:
crates/arroyo-storage/src/lib.rs:33 StorageProvider / :180 BackendConfig)."""

import numpy as np
import pytest

from arroyo_tpu.state import storage


class FakeS3:
    """Minimal in-memory S3 client: the five calls storage.py makes."""

    def __init__(self):
        self.objects: dict[tuple[str, str], bytes] = {}

    def put_object(self, Bucket, Key, Body):
        self.objects[(Bucket, Key)] = bytes(Body)

    def get_object(self, Bucket, Key):
        import io

        if (Bucket, Key) not in self.objects:
            raise KeyError(Key)
        return {"Body": io.BytesIO(self.objects[(Bucket, Key)])}

    def head_object(self, Bucket, Key):
        if (Bucket, Key) not in self.objects:
            raise KeyError(Key)
        return {}

    def delete_object(self, Bucket, Key):
        self.objects.pop((Bucket, Key), None)

    def list_objects_v2(self, Bucket, Prefix="", Delimiter=None, MaxKeys=1000,
                        ContinuationToken=None):
        keys = sorted(k for b, k in self.objects if b == Bucket and k.startswith(Prefix))
        contents, prefixes = [], set()
        for k in keys:
            rest = k[len(Prefix):]
            if Delimiter and Delimiter in rest:
                prefixes.add(Prefix + rest.split(Delimiter)[0] + Delimiter)
            else:
                contents.append({"Key": k})
        return {
            "Contents": contents[:MaxKeys],
            "CommonPrefixes": [{"Prefix": p} for p in sorted(prefixes)],
            "KeyCount": min(len(contents) + len(prefixes), MaxKeys),
        }


class FakeMultipartS3(FakeS3):
    """FakeS3 plus the multipart API: uploads assemble from parts and a
    failure mid-part must abort (no half-object visible)."""

    def __init__(self):
        super().__init__()
        self.uploads: dict[str, dict] = {}
        self.multipart_completed = 0
        self.aborted = 0
        self.fail_part: int | None = None

    def create_multipart_upload(self, Bucket, Key):
        uid = f"up-{len(self.uploads)}"
        self.uploads[uid] = {"bucket": Bucket, "key": Key, "parts": {}}
        return {"UploadId": uid}

    def upload_part(self, Bucket, Key, UploadId, PartNumber, Body):
        if self.fail_part == PartNumber:
            raise RuntimeError("injected part failure")
        self.uploads[UploadId]["parts"][PartNumber] = bytes(Body)
        return {"ETag": f"etag-{PartNumber}"}

    def complete_multipart_upload(self, Bucket, Key, UploadId, MultipartUpload):
        up = self.uploads.pop(UploadId)
        body = b"".join(up["parts"][p["PartNumber"]]
                        for p in MultipartUpload["Parts"])
        self.objects[(Bucket, Key)] = body
        self.multipart_completed += 1

    def abort_multipart_upload(self, Bucket, Key, UploadId):
        self.uploads.pop(UploadId, None)
        self.aborted += 1


class FakeGcs:
    """In-memory client with the GcsHttpClient surface."""

    def __init__(self):
        self.objects: dict[tuple[str, str], bytes] = {}

    def download(self, bucket, name):
        if (bucket, name) not in self.objects:
            raise FileNotFoundError(name)
        return self.objects[(bucket, name)]

    def upload(self, bucket, name, data):
        self.objects[(bucket, name)] = bytes(data)

    def delete(self, bucket, name):
        self.objects.pop((bucket, name), None)

    def exists(self, bucket, name):
        return (bucket, name) in self.objects

    def list(self, bucket, prefix, delimiter=None):
        names, prefixes = [], set()
        for (b, n) in sorted(self.objects):
            if b != bucket or not n.startswith(prefix):
                continue
            rest = n[len(prefix):]
            if delimiter and delimiter in rest:
                prefixes.add(prefix + rest.split(delimiter)[0] + delimiter)
            else:
                names.append(n)
        return names, sorted(prefixes)


@pytest.fixture
def fake_s3():
    client = FakeS3()
    storage.set_s3_client(client)
    yield client
    storage.set_s3_client(None)


@pytest.fixture
def fake_gcs():
    client = FakeGcs()
    storage.set_gcs_client(client)
    yield client
    storage.set_gcs_client(None)


def test_s3_bytes_listing_roundtrip(fake_s3):
    storage.write_bytes("s3://bkt/a/b/file.bin", b"hello")
    storage.write_text("s3://bkt/a/other.txt", "world")
    assert storage.read_bytes("s3://bkt/a/b/file.bin") == b"hello"
    assert storage.read_text("s3://bkt/a/other.txt") == "world"
    assert storage.exists("s3://bkt/a/other.txt")
    assert not storage.exists("s3://bkt/a/missing")
    assert storage.isdir("s3://bkt/a") and storage.isdir("s3://bkt/a/b")
    assert storage.listdir("s3://bkt/a") == ["b", "other.txt"]
    storage.remove("s3://bkt/a/other.txt")
    assert storage.listdir("s3://bkt/a") == ["b"]
    storage.rmtree("s3://bkt/a")
    assert not storage.isdir("s3://bkt/a")


def test_gcs_bytes_listing_roundtrip(fake_gcs):
    storage.write_bytes("gs://bkt/a/b/file.bin", b"hello")
    storage.write_text("gs://bkt/a/other.txt", "world")
    assert storage.read_bytes("gs://bkt/a/b/file.bin") == b"hello"
    assert storage.read_text("gs://bkt/a/other.txt") == "world"
    assert storage.exists("gs://bkt/a/other.txt")
    assert not storage.exists("gs://bkt/a/missing")
    assert storage.isdir("gs://bkt/a") and storage.isdir("gs://bkt/a/b")
    assert storage.listdir("gs://bkt/a") == ["b", "other.txt"]
    storage.remove("gs://bkt/a/other.txt")
    assert storage.listdir("gs://bkt/a") == ["b"]
    storage.rmtree("gs://bkt/a")
    assert not storage.isdir("gs://bkt/a")


def test_checkpoint_roundtrip_on_fake_gcs(fake_gcs):
    """Full state checkpoint/restore over gs:// paths (same flow as the S3
    test): the TableManager only sees the storage API."""
    from arroyo_tpu.batch import Batch, KEY_FIELD, TIMESTAMP_FIELD
    from arroyo_tpu.state.tables import TableManager
    from arroyo_tpu.types import TaskInfo

    from arroyo_tpu.operators.base import TableSpec

    ti = TaskInfo("jg", "op", "operator", 0, 1)
    tm = TableManager(ti, "gs://ckpt/jobs")
    tbl = tm.global_keyed("g")
    tbl.insert("k1", {"x": 1})
    tm.checkpoint(1, None)
    tm2 = TableManager(TaskInfo("jg", "op", "operator", 0, 1), "gs://ckpt/jobs")
    tm2.restore(1, [TableSpec("g", "global_keyed")])
    assert dict(tm2.global_keyed("g").items())["k1"] == {"x": 1}


def test_s3_multipart_write_and_abort(fake_s3, monkeypatch):
    """Writes above the threshold go through multipart (parts reassemble
    byte-exact); a failing part aborts the upload leaving no object."""
    client = FakeMultipartS3()
    storage.set_s3_client(client)
    from arroyo_tpu import config as cfg

    cfg.update({"storage.multipart-threshold-bytes": 1024,
                "storage.multipart-part-size-bytes": 1024})
    try:
        small = b"s" * 100
        storage.write_bytes("s3://bkt/small.bin", small)
        assert client.multipart_completed == 0  # under threshold: plain put
        big = bytes(range(256)) * 20  # 5120 bytes -> 5 parts at 1024
        storage.write_bytes("s3://bkt/big.bin", big)
        assert client.multipart_completed == 1
        assert storage.read_bytes("s3://bkt/big.bin") == big
        # failure mid-part: abort, no partial object, no leaked upload
        client.fail_part = 3
        with pytest.raises(RuntimeError, match="injected part failure"):
            storage.write_bytes("s3://bkt/fail.bin", big)
        assert client.aborted == 1
        assert not client.uploads
        assert not storage.exists("s3://bkt/fail.bin")
        # with no explicit part size, parts never go below the S3 minimum
        cfg.update({"storage.multipart-part-size-bytes": None})
        assert storage._multipart_part_size() == storage.S3_MIN_PART
    finally:
        cfg.update({"storage.multipart-threshold-bytes": None,
                    "storage.multipart-part-size-bytes": None})
        storage.set_s3_client(None)


def test_local_write_is_atomic_publish(tmp_path):
    p = str(tmp_path / "x.json")
    storage.write_text(p, "{}")
    assert storage.read_text(p) == "{}"
    assert storage.listdir(str(tmp_path)) == ["x.json"]  # no .tmp residue


def test_checkpoint_restore_roundtrip_on_fake_s3(fake_s3):
    """Full TableManager checkpoint -> restore cycle against s3:// URLs,
    including rescale (2 subtasks checkpoint, 1 restores everything)."""
    from arroyo_tpu.batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
    from arroyo_tpu.state.tables import (
        TableManager,
        latest_complete_checkpoint,
        write_job_checkpoint_metadata,
    )
    from arroyo_tpu.types import TaskInfo

    url = "s3://ckpt-bucket/prefix"
    full = (0, (1 << 64) - 1)
    for sub in range(2):
        ti = TaskInfo("job1", "agg", "agg", sub, 2)
        tm = TableManager(ti, url)
        tm.global_keyed("s").insert(sub, {"offset": 100 + sub})
        keys = (np.arange(4, dtype=np.int64) + 10 * sub).view(np.uint64)
        tbl = tm.expiring_time_key("t", retention_micros=10_000_000)
        tbl.insert(Batch({
            TIMESTAMP_FIELD: np.arange(4, dtype=np.int64) * 1000,
            KEY_FIELD: keys,
            "v": np.arange(4, dtype=np.int64) + 100 * sub,
        }))
        tm.checkpoint(epoch=1, watermark_micros=500)
    write_job_checkpoint_metadata(url, "job1", 1)
    assert latest_complete_checkpoint(url, "job1") == 1

    ti3 = TaskInfo("job1", "agg", "agg", 0, 1)  # rescale 2 -> 1
    tm3 = TableManager(ti3, url)

    class Spec:
        name = "t"
        retention_micros = 10_000_000

    wm = tm3.restore(1, [Spec()])
    assert wm == 500
    assert tm3.global_keyed("s").get(0) == {"offset": 100}
    assert tm3.global_keyed("s").get(1) == {"offset": 101}
    rows = sorted(
        int(v) for b in tm3.expiring_time_key("t").all_batches() for v in b["v"]
    )
    assert rows == [0, 1, 2, 3, 100, 101, 102, 103]


def test_compaction_on_fake_s3(fake_s3):
    """compact_operator merges per-subtask shards under s3:// and the
    compacted epoch still restores exactly."""
    from arroyo_tpu.batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
    from arroyo_tpu.state.tables import TableManager, compact_job
    from arroyo_tpu.types import TaskInfo

    url = "s3://ckpt-bucket/c"
    for sub in range(3):
        ti = TaskInfo("j", "op", "op", sub, 3)
        tm = TableManager(ti, url)
        keys = (np.arange(2, dtype=np.int64) + 5 * sub).view(np.uint64)
        tm.expiring_time_key("t", 1_000_000).insert(Batch({
            TIMESTAMP_FIELD: np.array([0, 1000], dtype=np.int64),
            KEY_FIELD: keys,
            "v": np.array([sub, sub + 10], dtype=np.int64),
        }))
        tm.checkpoint(epoch=2, watermark_micros=None)
    removed = compact_job(url, "j", 2)
    assert removed == 3  # three gen-0 shards merged away

    ti = TaskInfo("j", "op", "op", 0, 1)
    tm = TableManager(ti, url)

    class Spec:
        name = "t"
        retention_micros = 1_000_000

    tm.restore(2, [Spec()])
    rows = sorted(
        int(v) for b in tm.expiring_time_key("t").all_batches() for v in b["v"]
    )
    assert rows == [0, 1, 2, 10, 11, 12]


def test_npz_checkpoint_readable_when_parquet_default(tmp_path):
    """A state file written under the npz fallback must restore after the
    default codec flips to parquet: read_columnar keys off the extension."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.state.tables import read_columnar, write_columnar

    p = str(tmp_path / "table-t-000.npz")
    cfg.update({"checkpoint.file-format": "npz"})
    write_columnar(p, {"a": np.arange(5, dtype=np.int64),
                       "s": np.array(["x", None, "y", "z", "w"], dtype=object)})
    cfg.update({"checkpoint.file-format": "parquet"})
    cols = read_columnar(p)
    assert list(cols["a"]) == [0, 1, 2, 3, 4]
    assert list(cols["s"]) == ["x", None, "y", "z", "w"]


def test_parquet_heterogeneous_object_column_exact_roundtrip(tmp_path):
    """Mixed-type object columns survive checkpoint/restore exactly via the
    pickled-binary fallback (not stringified)."""
    from arroyo_tpu.state.tables import read_columnar, write_columnar

    p = str(tmp_path / "table-x-000.parquet")
    vals = np.array([42, "answer", None, 3.5, (1, 2)], dtype=object)
    write_columnar(p, {"m": vals, "d": np.arange(5, dtype=np.int64)})
    cols = read_columnar(p)
    assert list(cols["m"]) == [42, "answer", None, 3.5, (1, 2)]
    assert list(cols["d"]) == [0, 1, 2, 3, 4]
