"""Connector suite: filesystem, http family, websocket, redis, preview.

All network connectors are driven against local in-test servers (the
reference similarly unit-tests kafka/mqtt against local brokers, §4.4).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import arroyo_tpu
from arroyo_tpu import config as cfg
from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
from arroyo_tpu.engine.engine import Engine, run_graph
from arroyo_tpu.graph import EdgeType, Graph, Node, OpName
from arroyo_tpu.sql import plan_query


def _graph_src_sink(src_cfg, sink_cfg, schema):
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, src_cfg, 1))
    g.add_node(Node("sink", OpName.SINK, sink_cfg, 1))
    g.add_edge("src", "sink", EdgeType.FORWARD, schema)
    return g


SCHEMA = Schema.of([("x", "int64"), ("name", "string"), (TIMESTAMP_FIELD, "int64")])


def _write_json_input(path, n=50):
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({"x": i, "name": f"n{i}", "_timestamp": 1000 + i}) + "\n")


# --------------------------------------------------------------------- files


@pytest.mark.parametrize("fmt", ["json", "parquet", "avro"])
def test_filesystem_roundtrip(fmt, tmp_path, _storage):
    arroyo_tpu._load_operators()
    src_dir = tmp_path / "in"
    os.makedirs(src_dir)
    _write_json_input(src_dir / "a.json")
    out_dir = str(tmp_path / f"out_{fmt}")
    # stage 1: json -> fmt
    g = _graph_src_sink(
        {"connector": "filesystem", "path": str(src_dir), "format": "json",
         "schema": SCHEMA},
        {"connector": "filesystem", "path": out_dir, "format": fmt, "schema": SCHEMA},
        SCHEMA,
    )
    run_graph(g, job_id=f"fs1-{fmt}", timeout=60)
    files = os.listdir(out_dir)
    assert files, "sink wrote no part files"
    # stage 2: read the fmt back
    rows = []
    g2 = _graph_src_sink(
        {"connector": "filesystem", "path": out_dir, "format": fmt, "schema": SCHEMA},
        {"connector": "vec", "rows": rows},
        SCHEMA,
    )
    run_graph(g2, job_id=f"fs2-{fmt}", timeout=60)
    assert sorted(r["x"] for r in rows) == list(range(50))
    assert sorted(r["name"] for r in rows) == sorted(f"n{i}" for i in range(50))


def test_filesystem_sink_partitioning_and_commit(tmp_path, _storage):
    """Partitioned part files only appear after the epoch's commit phase."""
    arroyo_tpu._load_operators()
    src = tmp_path / "in.json"
    _write_json_input(src, 40)
    out_dir = str(tmp_path / "parts")
    g = _graph_src_sink(
        {"connector": "filesystem", "path": str(src), "format": "json",
         "schema": SCHEMA},
        {"connector": "filesystem", "path": out_dir, "format": "json",
         "schema": SCHEMA, "partition_fields": ["x_mod"]},
        SCHEMA,
    )
    # add partition column via a VALUE node
    from arroyo_tpu.expr import BinOp, Col, Lit

    g.nodes.pop("sink")
    g.edges.clear()
    g.add_node(Node("proj", OpName.VALUE, {"projections": [
        ("x", Col("x")), ("name", Col("name")),
        ("x_mod", BinOp("%", Col("x"), Lit(2))),
    ]}, 1))
    g.add_node(Node("sink", OpName.SINK, {
        "connector": "filesystem", "path": out_dir, "format": "json",
        "schema": SCHEMA, "partition_fields": ["x_mod"]}, 1))
    g.add_edge("src", "proj", EdgeType.FORWARD, SCHEMA)
    g.add_edge("proj", "sink", EdgeType.FORWARD, SCHEMA)
    run_graph(g, job_id="fs-part", timeout=60)
    assert sorted(os.listdir(out_dir)) == ["x_mod=0", "x_mod=1"]
    n = 0
    for d in ("x_mod=0", "x_mod=1"):
        for fn in os.listdir(os.path.join(out_dir, d)):
            with open(os.path.join(out_dir, d, fn)) as f:
                n += sum(1 for _ in f)
    assert n == 40


def test_filesystem_exactly_once_across_restore(tmp_path, _storage):
    """Checkpoint mid-stream, stop, restore: no duplicate part rows."""
    arroyo_tpu._load_operators()
    src = tmp_path / "in.json"
    _write_json_input(src, 60)
    out_dir = str(tmp_path / "eo")
    cfg.update({"testing.source-read-delay-micros": 3000})

    def build():
        return Engine(_graph_src_sink(
            {"connector": "filesystem", "path": str(src), "format": "json",
             "schema": SCHEMA},
            {"connector": "filesystem", "path": out_dir, "format": "json",
             "schema": SCHEMA},
            SCHEMA,
        ), job_id="fs-eo")

    try:
        eng = build()
        eng.start()
        time.sleep(0.05)
        assert eng.checkpoint_and_wait(1, timeout=60)
        time.sleep(0.05)
        stopped = eng.checkpoint_and_wait(2, timeout=60, then_stop=True)
        eng.join(timeout=60)
    finally:
        cfg.update({"testing.source-read-delay-micros": 0})
    if stopped:
        eng2 = Engine(_graph_src_sink(
            {"connector": "filesystem", "path": str(src), "format": "json",
             "schema": SCHEMA},
            {"connector": "filesystem", "path": out_dir, "format": "json",
             "schema": SCHEMA},
            SCHEMA,
        ), job_id="fs-eo", restore_epoch=2)
        eng2.run_to_completion(timeout=60)
    rows = []
    for fn in sorted(os.listdir(out_dir)):
        with open(os.path.join(out_dir, fn)) as f:
            rows.extend(json.loads(l)["x"] for l in f if l.strip())
    assert sorted(rows) == list(range(60))


# ----------------------------------------------------------------- http/sse


def _http_server(handler_cls):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv


def test_polling_http_source(_storage):
    arroyo_tpu._load_operators()
    calls = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            calls.append(1)
            body = "\n".join(
                json.dumps({"x": len(calls) * 10 + i, "name": "p"}) for i in range(2)
            ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = _http_server(H)
    rows = []
    g = _graph_src_sink(
        {"connector": "polling_http", "endpoint": f"http://127.0.0.1:{srv.server_port}/",
         "poll_interval_ms": 10, "schema": SCHEMA, "testing.max_polls": 3},
        {"connector": "vec", "rows": rows},
        SCHEMA,
    )
    run_graph(g, job_id="poll", timeout=60)
    srv.shutdown()
    assert len(rows) == 6
    assert {r["x"] for r in rows} == {10, 11, 20, 21, 30, 31}


def test_webhook_sink(tmp_path, _storage):
    arroyo_tpu._load_operators()
    received = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers["Content-Length"])
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

    srv = _http_server(H)
    src = tmp_path / "in.json"
    _write_json_input(src, 5)
    g = _graph_src_sink(
        {"connector": "single_file", "path": str(src), "schema": SCHEMA},
        {"connector": "webhook", "endpoint": f"http://127.0.0.1:{srv.server_port}/",
         "schema": SCHEMA},
        SCHEMA,
    )
    run_graph(g, job_id="hook", timeout=60)
    srv.shutdown()
    assert sorted(r["x"] for r in received) == list(range(5))


def test_sse_source(_storage):
    arroyo_tpu._load_operators()

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.end_headers()
            for i in range(4):
                payload = json.dumps({"x": i, "name": f"e{i}"})
                self.wfile.write(f"id: {i}\ndata: {payload}\n\n".encode())
            self.wfile.write(b"event: other\ndata: {}\n\n")  # filtered out
            # close the stream -> source finishes gracefully

    srv = _http_server(H)
    rows = []
    g = _graph_src_sink(
        {"connector": "sse", "endpoint": f"http://127.0.0.1:{srv.server_port}/",
         "events": "message", "schema": SCHEMA},
        {"connector": "vec", "rows": rows},
        SCHEMA,
    )
    run_graph(g, job_id="sse", timeout=60)
    srv.shutdown()
    assert sorted(r["x"] for r in rows) == [0, 1, 2, 3]


# ---------------------------------------------------------------- websocket


def test_websocket_source(_storage):
    arroyo_tpu._load_operators()
    from arroyo_tpu.connectors.websocket import (
        OP_CLOSE,
        OP_TEXT,
        FrameReader,
        accept_handshake,
        encode_frame,
    )

    server = socket.create_server(("127.0.0.1", 0))
    port = server.getsockname()[1]
    got_subscription = []

    def serve():
        conn, _ = server.accept()
        accept_handshake(conn)
        reader = FrameReader()
        # read the subscription message
        while not got_subscription:
            for op, payload in reader.feed(conn.recv(4096)):
                if op == OP_TEXT:
                    got_subscription.append(payload.decode())
        for i in range(3):
            msg = json.dumps({"x": i, "name": f"w{i}"}).encode()
            conn.sendall(encode_frame(OP_TEXT, msg, mask=False))
        conn.sendall(encode_frame(OP_CLOSE, b"", mask=False))
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    rows = []
    g = _graph_src_sink(
        {"connector": "websocket", "endpoint": f"ws://127.0.0.1:{port}/feed",
         "subscription_message": '{"subscribe": "all"}', "schema": SCHEMA},
        {"connector": "vec", "rows": rows},
        SCHEMA,
    )
    run_graph(g, job_id="ws", timeout=60)
    server.close()
    assert got_subscription == ['{"subscribe": "all"}']
    assert sorted(r["x"] for r in rows) == [0, 1, 2]


# -------------------------------------------------------------------- redis


class _FakeRedis:
    """RESP2 server speaking SET/RPUSH/HSET/GET for tests."""

    def __init__(self):
        self.server = socket.create_server(("127.0.0.1", 0))
        self.port = self.server.getsockname()[1]
        self.data: dict = {}
        self.lists: dict = {}
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        while True:
            try:
                conn, _ = self.server.accept()
            except OSError:
                return
            threading.Thread(target=self._client, args=(conn,), daemon=True).start()

    def _client(self, conn):
        buf = b""
        while True:
            try:
                chunk = conn.recv(65536)
            except OSError:
                return
            if not chunk:
                return
            buf += chunk
            while True:
                cmd, buf2 = self._parse(buf)
                if cmd is None:
                    break
                buf = buf2
                conn.sendall(self._exec(cmd))

    def _parse(self, buf):
        if not buf.startswith(b"*") or b"\r\n" not in buf:
            return None, buf
        head, rest = buf.split(b"\r\n", 1)
        n = int(head[1:])
        args = []
        for _ in range(n):
            if not rest.startswith(b"$") or b"\r\n" not in rest:
                return None, buf
            lhead, rest2 = rest.split(b"\r\n", 1)
            ln = int(lhead[1:])
            if len(rest2) < ln + 2:
                return None, buf
            args.append(rest2[:ln])
            rest = rest2[ln + 2 :]
        return args, rest

    def _exec(self, args):
        cmd = args[0].upper()
        if cmd == b"SET":
            self.data[args[1]] = args[2]
            return b"+OK\r\n"
        if cmd == b"RPUSH":
            self.lists.setdefault(args[1], []).append(args[2])
            return f":{len(self.lists[args[1]])}\r\n".encode()
        if cmd == b"HSET":
            self.data[(args[1], args[2])] = args[3]
            return b":1\r\n"
        if cmd == b"GET":
            v = self.data.get(args[1])
            if v is None:
                return b"$-1\r\n"
            return f"${len(v)}\r\n".encode() + v + b"\r\n"
        return b"-ERR unknown\r\n"


def test_redis_sink_and_lookup(tmp_path, _storage):
    arroyo_tpu._load_operators()
    fake = _FakeRedis()
    src = tmp_path / "in.json"
    _write_json_input(src, 4)
    g = _graph_src_sink(
        {"connector": "single_file", "path": str(src), "schema": SCHEMA},
        {"connector": "redis", "host": "127.0.0.1", "port": fake.port,
         "target": "string", "key_prefix": "row:", "key_field": "x",
         "schema": SCHEMA},
        SCHEMA,
    )
    run_graph(g, job_id="redis", timeout=60)
    assert json.loads(fake.data[b"row:2"])["name"] == "n2"
    # lookup side
    from arroyo_tpu.connectors.redis import RedisLookup

    lk = RedisLookup({"host": "127.0.0.1", "port": fake.port, "key_prefix": "row:"})
    res = lk.lookup([1, 3, 99])
    assert res[1]["name"] == "n1" and res[3]["name"] == "n3" and res[99] is None
    fake.server.close()


# ------------------------------------------------------------------ preview


def test_preview_rows_via_rest(tmp_path, _storage):
    import urllib.request

    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler

    arroyo_tpu._load_operators()
    src = tmp_path / "in.json"
    _write_json_input(src, 8)
    sql = f"""
    CREATE TABLE t (x BIGINT, name TEXT) WITH (
      connector = 'single_file', path = '{src}', format = 'json', type = 'source');
    SELECT x * 2 AS двух FROM t WHERE x < 4;
    """
    # non-ascii alias exercises ident handling too; rename for clarity:
    sql = sql.replace("двух", "doubled")
    db = Database()
    api = ApiServer(db, port=0).start()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        body = json.dumps({"name": "preview", "query": sql}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{api.port}/api/v1/pipelines", data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        jid = json.loads(urllib.request.urlopen(req).read())["job_id"]
        ctl.wait_for_state(jid, "Finished", timeout=60)
        deadline = time.monotonic() + 10
        rows = []
        while time.monotonic() < deadline and len(rows) < 4:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{api.port}/api/v1/jobs/{jid}/output"
            ) as resp:
                rows = json.loads(resp.read())["data"]
            time.sleep(0.05)
        vals = sorted(json.loads(r["line"])["doubled"] for r in rows)
        assert vals == [0, 2, 4, 6]
    finally:
        ctl.stop()
        api.stop()


def test_gated_connector_raises_helpfully(_storage):
    # mqtt/nats/rabbitmq/kinesis grew real from-scratch implementations;
    # fluvio remains gated on its client package (no public wire spec)
    arroyo_tpu._load_operators()
    from arroyo_tpu.connectors import _SOURCES

    with pytest.raises(ImportError, match="fluvio"):
        _SOURCES["fluvio"]({"endpoint": "x"})


def test_connector_registry_lists_all(_storage):
    from arroyo_tpu.connectors import connectors

    c = connectors()
    for name in ("kafka", "filesystem", "sse", "websocket", "polling_http",
                 "single_file", "impulse", "nexmark", "kinesis", "mqtt", "nats",
                 "rabbitmq", "fluvio"):
        assert name in c["sources"], name
    for name in ("kafka", "filesystem", "webhook", "redis", "preview",
                 "single_file", "stdout", "blackhole"):
        assert name in c["sinks"], name


def test_filesystem_commit_on_checkpoint_stop(tmp_path, _storage):
    """then_stop must finalize the stopping epoch's part files: the commit
    phase runs before the sink task exits (regression: stop-with-checkpoint
    used to leave the output directory empty)."""
    arroyo_tpu._load_operators()
    src = tmp_path / "in.json"
    _write_json_input(src, 30)
    out_dir = str(tmp_path / "cs")
    cfg.update({"testing.source-read-delay-micros": 3000})
    try:
        eng = Engine(_graph_src_sink(
            {"connector": "filesystem", "path": str(src), "format": "json",
             "schema": SCHEMA},
            {"connector": "filesystem", "path": out_dir, "format": "json",
             "schema": SCHEMA},
            SCHEMA,
        ), job_id="fs-cs")
        eng.start()
        time.sleep(0.05)
        stopped = eng.checkpoint_and_wait(1, timeout=60, then_stop=True)
        eng.join(timeout=60)
    finally:
        cfg.update({"testing.source-read-delay-micros": 0})
    if stopped:
        rows = []
        for fn in sorted(os.listdir(out_dir)):
            with open(os.path.join(out_dir, fn)) as f:
                rows.extend(json.loads(l)["x"] for l in f if l.strip())
        assert rows, "stopping epoch was never committed"
        assert len(rows) == len(set(rows))
        # restore finishes the stream with no duplicates
        eng2 = Engine(_graph_src_sink(
            {"connector": "filesystem", "path": str(src), "format": "json",
             "schema": SCHEMA},
            {"connector": "filesystem", "path": out_dir, "format": "json",
             "schema": SCHEMA},
            SCHEMA,
        ), job_id="fs-cs", restore_epoch=1)
        eng2.run_to_completion(timeout=60)
        rows = []
        for fn in sorted(os.listdir(out_dir)):
            with open(os.path.join(out_dir, fn)) as f:
                rows.extend(json.loads(l)["x"] for l in f if l.strip())
        assert sorted(rows) == list(range(30))


def test_kafka_offset_tracker_rescale():
    from arroyo_tpu.connectors.kafka import _OffsetTracker

    t = _OffsetTracker()
    t.merge({0: 100, 2: 50})   # old subtask 0 (p=2)
    t.merge({1: 70, 3: 90})    # old subtask 1 (p=2)
    assert t.resume_position(1) == 70 and t.resume_position(3) == 90
    assert t.partitions_for(0, 1, 4) == [0, 1, 2, 3]
    t.observe(1, 75)
    assert t.resume_position(1) == 76


def test_kafka_auth_options_pass_through():
    """security./sasl./ssl. options (a Confluent Cloud profile) and
    librdkafka.-prefixed options reach the client config verbatim; format
    options do not leak in."""
    from arroyo_tpu.connectors.kafka import _auth_conf

    c = _auth_conf({
        "bootstrap_servers": "b:9092", "format": "json", "topic": "t",
        "security.protocol": "SASL_SSL", "sasl.mechanisms": "PLAIN",
        "sasl.username": "API_KEY", "sasl.password": "API_SECRET",
        "ssl.ca.location": "/etc/ssl/ca.pem",
        "librdkafka.client.id": "arroyo-tpu",
    })
    assert c == {
        "security.protocol": "SASL_SSL", "sasl.mechanisms": "PLAIN",
        "sasl.username": "API_KEY", "sasl.password": "API_SECRET",
        "ssl.ca.location": "/etc/ssl/ca.pem", "client.id": "arroyo-tpu",
    }
