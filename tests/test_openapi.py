"""OpenAPI spec + generated-client parity (reference arroyo-openapi +
integ/tests/api_tests.rs): the spec is served by the API, the client covers
every operation, and a client-driven pipeline lifecycle runs end-to-end."""

import json
import os


def test_spec_served_and_valid():
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.api.client import ArroyoClient
    from arroyo_tpu.controller import Database

    api = ApiServer(Database()).start()
    try:
        c = ArroyoClient(f"http://127.0.0.1:{api.port}")
        spec = c._req("GET", "/api/v1/openapi.json")
        assert spec["openapi"].startswith("3.")
        assert "/api/v1/pipelines" in spec["paths"]
    finally:
        api.stop()


def test_client_covers_every_operation():
    """Every operationId in the spec has a client method; every documented
    path is dispatchable by the server's route table."""
    import re

    from arroyo_tpu.api.client import ArroyoClient
    from arroyo_tpu.api.openapi import spec
    from arroyo_tpu.api.server import ApiServer

    ops = []
    for path, methods in spec()["paths"].items():
        for method, op in methods.items():
            ops.append((method.upper(), path, op["operationId"]))
    for _m, _p, op_id in ops:
        assert hasattr(ArroyoClient, op_id), f"client missing {op_id}"
    # spec paths must be matched by server routes (templated -> concrete)
    for method, path, op_id in ops:
        concrete = re.sub(r"\{[^}]+\}", "x", path)
        matched = any(
            m == method and re.match(pat, concrete)
            for m, pat, _name in ApiServer._ROUTES
        )
        assert matched, f"no server route for {method} {path}"


def test_client_driven_job_lifecycle(tmp_path, _storage):
    from arroyo_tpu import config as cfg
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.api.client import ApiError, ArroyoClient
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler

    inp = tmp_path / "in.json"
    with open(inp, "w") as f:
        for i in range(30):
            f.write(json.dumps({"x": i, "timestamp": i * 1000}) + "\n")
    out_path = tmp_path / "out.json"
    sql = f"""
CREATE TABLE src (timestamp TIMESTAMP, x BIGINT)
WITH (connector = 'single_file', path = '{inp}', format = 'json', type = 'source', event_time_field = 'timestamp');
CREATE TABLE snk (x BIGINT)
WITH (connector = 'single_file', path = '{out_path}', format = 'json', type = 'sink');
INSERT INTO snk SELECT x FROM src WHERE x % 2 = 0;
"""
    db = Database()
    api = ApiServer(db).start()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        c = ArroyoClient(f"http://127.0.0.1:{api.port}")
        assert c.ping()["pong"]
        assert c.validate_query(sql)["valid"]
        assert not c.validate_query("SELECT nonsense FROM nowhere")["valid"]
        r = c.create_pipeline(sql, name="clientpipe")
        job = c.run_to_state(r["job_id"], "Finished")
        assert job["state"] == "Finished"
        assert [p["name"] for p in c.list_pipelines()] == ["clientpipe"]
        assert len(c.pipeline_jobs(r["id"])) == 1
        rows = [json.loads(l) for l in open(out_path)]
        assert len(rows) == 15
        try:
            c.get_pipeline("pl_nope")
            raise AssertionError("expected 404")
        except ApiError as e:
            assert e.status == 404
    finally:
        ctl.stop()
        api.stop()


def test_connection_table_crud_and_sql_by_name(tmp_path, _storage):
    """Connection tables registered over REST are usable in pipeline SQL by
    name with no inline DDL (reference rest.rs:144-158 CRUD +
    ArroyoSchemaProvider registration)."""
    import time

    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.api.client import ApiError, ArroyoClient
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler

    inp = tmp_path / "in.json"
    with open(inp, "w") as f:
        for i in range(20):
            f.write(json.dumps({"x": i, "timestamp": i * 1000}) + "\n")
    out_path = tmp_path / "out.json"
    db = Database()
    api = ApiServer(db).start()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        c = ArroyoClient(f"http://127.0.0.1:{api.port}")
        # profile holds shared options; table overrides/extends them
        prof = c.create_connection_profile("files", "single_file",
                                           {"format": "json"})
        t = c.test_connection_table(
            name="events", connector="single_file", table_type="source",
            schema_fields=[{"name": "timestamp", "type": "TIMESTAMP"},
                           {"name": "x", "type": "BIGINT"}])
        assert t["ok"], t
        bad = c.test_connection_table(name="b", connector="nope")
        assert not bad["ok"] and "unknown source connector" in bad["error"]
        src = c.create_connection_table(
            "events", "single_file", "source",
            config={"path": str(inp), "event_time_field": "timestamp"},
            schema_fields=[{"name": "timestamp", "type": "TIMESTAMP"},
                           {"name": "x", "type": "BIGINT"}],
            profile_id=prof["id"])
        snk = c.create_connection_table(
            "out_events", "single_file", "sink",
            config={"path": str(out_path)},
            schema_fields=[{"name": "x", "type": "BIGINT"}],
            profile_id=prof["id"])
        names = [t["name"] for t in c.list_connection_tables()]
        assert names == ["events", "out_events"]
        # profile config merged in (format riding from the profile)
        assert all(t["config"]["format"] == "json"
                   for t in c.list_connection_tables())

        # SQL references both by NAME — no CREATE TABLE anywhere
        sql = "INSERT INTO out_events SELECT x FROM events WHERE x < 10;"
        assert c.validate_query(sql)["valid"]
        r = c.create_pipeline(sql, name="ct-pipe")
        job = c.run_to_state(r["job_id"], "Finished")
        assert job["state"] == "Finished"
        rows = [json.loads(l) for l in open(out_path)]
        assert sorted(row["x"] for row in rows) == list(range(10))

        # a profile referenced by tables cannot be deleted
        try:
            c.delete_connection_profile(prof["id"])
            raise AssertionError("expected 409")
        except ApiError as e:
            assert e.status == 409
        c.delete_connection_table(src["id"])
        c.delete_connection_table(snk["id"])
        c.delete_connection_profile(prof["id"])
        assert c.list_connection_tables() == []
        assert not c.validate_query(sql)["valid"]  # tables gone from scope
    finally:
        ctl.stop()
        api.stop()


def test_webui_served():
    """Shell + every ES module asset serve with correct content types; a
    traversal-shaped asset name 404s."""
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import Database
    import urllib.error
    import urllib.request

    api = ApiServer(Database()).start()
    base = f"http://127.0.0.1:{api.port}"
    try:
        with urllib.request.urlopen(f"{base}/") as r:
            body = r.read().decode()
        assert "arroyo-tpu console" in body
        assert "/webui/app.js" in body
        for asset, ctype in [("app.js", "text/javascript"),
                             ("jobs.js", "text/javascript"),
                             ("pipelines.js", "text/javascript"),
                             ("connections.js", "text/javascript"),
                             ("udfs.js", "text/javascript"),
                             ("graph.js", "text/javascript"),
                             ("charts.js", "text/javascript"),
                             ("styles.css", "text/css")]:
            with urllib.request.urlopen(f"{base}/webui/{asset}") as r:
                assert r.headers["Content-Type"].startswith(ctype), asset
                assert r.read()
        try:
            urllib.request.urlopen(f"{base}/webui/..%2Fapi%2Fserver.py")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        api.stop()


def test_webui_endpoints_match_renderers(tmp_path, _storage):
    """Every API path the SPA modules fetch is dispatchable by the server,
    and the payloads carry the exact fields the renderers read (graph:
    nodes id/op/description/parallelism + edges src/dst/type; metrics:
    messages_per_sec/backpressure/sent; checkpoints: epoch/state/time;
    output: line)."""
    import glob
    import re
    import urllib.request

    from arroyo_tpu import config as cfg
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.api.server import ApiServer as _AS
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler

    webui = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "arroyo_tpu", "webui")
    called = set()
    for js in glob.glob(os.path.join(webui, "*.js")):
        src = open(js).read()
        for m, path in re.findall(
                r'api\(\s*"(GET|POST|PATCH|DELETE)",\s*[`"]([^`"]+)[`"]', src):
            # template literals -> concrete path segments
            called.add((m, re.sub(r"\$\{[^}]*\}", "x", path)))
    assert len(called) >= 12, f"UI call scan looks broken: {called}"
    for method, path in called:
        matched = any(
            m == method and re.match(pat, path)
            for m, pat, _name in _AS._ROUTES)
        assert matched, f"UI calls unrouted endpoint {method} {path}"

    # payload shapes, against a live job
    inp = tmp_path / "in.json"
    with open(inp, "w") as f:
        for i in range(20):
            f.write(json.dumps({"x": i, "timestamp": i * 1000}) + "\n")
    out_path = tmp_path / "out.json"
    sql = f"""
CREATE TABLE src (timestamp TIMESTAMP, x BIGINT)
WITH (connector = 'single_file', path = '{inp}', format = 'json', type = 'source', event_time_field = 'timestamp');
CREATE TABLE snk (x BIGINT)
WITH (connector = 'single_file', path = '{out_path}', format = 'json', type = 'sink');
INSERT INTO snk SELECT x FROM src;
"""
    cfg.update({"checkpoint.interval-ms": 150})
    db = Database()
    api = ApiServer(db).start()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{api.port}{path}") as r:
                return json.loads(r.read())

        pid = db.create_pipeline("ui-pipe", sql, 1)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Finished", timeout=60)

        g = get(f"/api/v1/pipelines/{pid}/graph")
        assert g["nodes"] and g["edges"]
        for n in g["nodes"]:
            assert {"id", "op", "description", "parallelism"} <= set(n)
        ops = {n["op"] for n in g["nodes"]}
        assert "source" in ops and "sink" in ops
        ids = {n["id"] for n in g["nodes"]}
        for e in g["edges"]:
            assert e["src"] in ids and e["dst"] in ids and "type" in e

        m = get(f"/api/v1/jobs/{jid}/metrics")["data"]
        assert m, "metrics empty"
        for v in m.values():
            assert "backpressure" in v
            assert "arroyo_worker_messages_sent" in v
            assert "messages_per_sec" in v

        cks = get(f"/api/v1/jobs/{jid}/checkpoints")["data"]
        if cks:
            assert {"epoch", "state", "time"} <= set(cks[0])
    finally:
        cfg.update({"checkpoint.interval-ms": 10_000})
        ctl.stop()
        api.stop()


def test_api_auth_token_gates_mutations(_storage):
    """With api.auth-token set, mutating requests need the bearer token
    (401 otherwise); reads stay open; the typed client and node-daemon
    POST helper pick the token up from config (ADVICE r4 trust model)."""
    import urllib.error
    import urllib.request

    from arroyo_tpu import config as cfg
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.api.client import ArroyoClient
    from arroyo_tpu.controller import Database
    from arroyo_tpu.controller.node import _post

    cfg.update({"api.auth-token": "s3cret"})
    try:
        api = ApiServer(Database()).start()
        base = f"http://127.0.0.1:{api.port}"
        try:
            # reads open
            with urllib.request.urlopen(f"{base}/api/v1/jobs") as r:
                assert r.status == 200
            # bare mutation -> 401
            req = urllib.request.Request(
                f"{base}/api/v1/pipelines/validate",
                data=json.dumps({"query": "SELECT 1"}).encode(),
                method="POST", headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req)
                raise AssertionError("expected 401")
            except urllib.error.HTTPError as e:
                assert e.code == 401
            # wrong token -> 401
            req.add_header("Authorization", "Bearer nope")
            try:
                urllib.request.urlopen(req)
                raise AssertionError("expected 401")
            except urllib.error.HTTPError as e:
                assert e.code == 401
            # typed client + node-daemon _post carry the config token
            c = ArroyoClient(base)
            assert not c.validate_query("SELEC nope")["valid"]
            assert _post(f"{base}/api/v1/nodes/register",
                         {"node_id": "n1", "addr": "http://x", "slots": 1})
        finally:
            api.stop()
    finally:
        cfg.update({"api.auth-token": None})
