"""Differential tests: jax hash-table aggregator vs the numpy oracle."""

import numpy as np
import pytest

from arroyo_tpu.ops import DeviceHashAggregator


def _random_stream(rng, n, n_keys, n_bins):
    keys = rng.integers(0, n_keys, size=n).astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    bins = rng.integers(0, n_bins, size=n).astype(np.int32)
    vals = rng.integers(1, 1000, size=n).astype(np.int64)
    return keys, bins, vals


def _as_dict(keys, bins, accs):
    return {
        (int(b), int(k)): tuple(int(a[i]) if np.issubdtype(a.dtype, np.integer) else float(a[i]) for a in accs)
        for i, (k, b) in enumerate(zip(keys.tolist(), bins.tolist()))
    }


@pytest.mark.parametrize("acc_kinds,acc_dtypes", [
    (("sum", "count"), (np.int64, np.int64)),
    (("min", "max"), (np.int64, np.int64)),
    (("sum",), (np.float64,)),
])
def test_jax_matches_numpy(acc_kinds, acc_dtypes):
    rng = np.random.default_rng(42)
    jx = DeviceHashAggregator(acc_kinds, acc_dtypes, cap=1024, batch_cap=256,
                              max_probes=64, emit_cap=128, backend="jax")
    ora = DeviceHashAggregator(acc_kinds, acc_dtypes, backend="numpy")
    for _ in range(5):
        keys, bins, vals = _random_stream(rng, 700, n_keys=50, n_bins=4)
        ins = []
        for k in acc_kinds:
            ins.append(np.ones(len(keys), dtype=np.int64) if k == "count" else vals)
        jx.update(keys, bins, ins)
        ora.update(keys, bins, ins)
    jk, jb, ja = jx.extract(0, 10, 10)
    ok, ob, oa = ora.extract(0, 10, 10)
    assert _as_dict(jk, jb, ja) == _as_dict(ok, ob, oa)


def test_extract_respects_ranges_and_freeing():
    agg = DeviceHashAggregator(("count",), (np.int64,), cap=256, batch_cap=64,
                               max_probes=32, emit_cap=64, backend="jax")
    keys = np.arange(10, dtype=np.uint64)
    ones = np.ones(10, dtype=np.int64)
    for b in range(4):
        agg.update(keys, np.full(10, b, dtype=np.int32), [ones])
    # non-destructive range scan of bins [1,3), nothing freed
    k, b, a = agg.extract(1, 3, 0)
    assert len(k) == 20 and set(b.tolist()) == {1, 2}
    # still there
    k2, b2, _ = agg.extract(1, 3, 0)
    assert len(k2) == 20
    # destructive close of bins < 2
    k3, b3, _ = agg.extract(0, 2, 2)
    assert len(k3) == 20 and set(b3.tolist()) == {0, 1}
    k4, _, _ = agg.extract(0, 10, 0)
    assert len(k4) == 20  # only bins 2,3 remain


def test_emit_cap_chunking():
    agg = DeviceHashAggregator(("count",), (np.int64,), cap=2048, batch_cap=512,
                               max_probes=64, emit_cap=64, backend="jax")
    keys = np.arange(500, dtype=np.uint64)
    agg.update(keys, np.zeros(500, dtype=np.int32), [np.ones(500, dtype=np.int64)])
    k, b, a = agg.extract(0, 1, 1)
    assert len(k) == 500  # drained across multiple extract calls
    assert sorted(np.asarray(k).tolist()) == list(range(500))


def test_overflow_raises_at_extract():
    """Overflow accumulates on device and is surfaced at the next
    extract/snapshot boundary (no per-batch host sync)."""
    agg = DeviceHashAggregator(("count",), (np.int64,), cap=64, batch_cap=256,
                               max_probes=8, emit_cap=64, backend="jax")
    keys = np.arange(200, dtype=np.uint64)
    agg.update(keys, np.zeros(200, dtype=np.int32), [np.ones(200, dtype=np.int64)])
    with pytest.raises(RuntimeError, match="overflow"):
        agg.extract(0, 1, 1)


def test_null_string_keys_hash():
    from arroyo_tpu.hashing import hash_column

    col = np.array(["a", None, "b", None, "a"], dtype=object)
    h = hash_column(col)
    assert h[0] == h[4] and h[1] == h[3] and h[0] != h[1] != h[2]


def test_scan_range_nondivisible_emit_cap():
    """emit_cap not dividing cap must not duplicate the last slot (gather
    indices past cap clamp to cap-1 under jit)."""
    agg = DeviceHashAggregator(("count",), (np.int64,), cap=64, batch_cap=64,
                               max_probes=64, emit_cap=48, backend="jax")
    keys = np.arange(40, dtype=np.uint64)
    agg.update(keys, np.zeros(40, dtype=np.int32), [np.ones(40, dtype=np.int64)])
    k, b, a = agg.scan_range(0, 1)
    assert len(k) == 40
    assert sorted(np.asarray(k).tolist()) == list(range(40))
    assert a[0].sum() == 40
    # non-destructive: second scan sees the same entries
    k2, _, _ = agg.scan_range(0, 1)
    assert len(k2) == 40
    agg.free_bins_below(1)
    k3, _, _ = agg.scan_range(0, 1)
    assert len(k3) == 0


def test_probe_hole_no_duplicate_entries():
    """Freeing closed bins punches holes in linear-probe chains; a later
    update of a live (key, bin) must not surface as two emitted rows.
    Differential test: interleaved updates + incremental closes, jax vs the
    dict-based numpy oracle."""
    rng = np.random.default_rng(7)
    kwargs = dict(cap=256, batch_cap=128, max_probes=256, emit_cap=64)
    jx = DeviceHashAggregator(("count",), (np.int64,), backend="jax", **kwargs)
    orc = DeviceHashAggregator(("count",), (np.int64,), backend="numpy", **kwargs)
    got, want = {}, {}
    for step in range(30):
        n = 100
        keys = rng.integers(0, 40, n).astype(np.uint64)
        bins = rng.integers(step // 3, step // 3 + 3, n).astype(np.int32)
        ones = np.ones(n, dtype=np.int64)
        jx.update(keys, bins, [ones])
        orc.update(keys, bins, [ones])
        if step % 3 == 2:
            close = step // 3 + 1
            for agg, out in ((jx, got), (orc, want)):
                k, b, a = agg.extract(0, close, close)
                for kk, bb, aa in zip(k.tolist(), b.tolist(), a[0].tolist()):
                    assert (kk, bb) not in out, f"duplicate entry {(kk, bb)}"
                    out[(kk, bb)] = aa
    for agg, out in ((jx, got), (orc, want)):
        k, b, a = agg.extract(0, 1 << 30, 1 << 30)
        for kk, bb, aa in zip(k.tolist(), b.tolist(), a[0].tolist()):
            assert (kk, bb) not in out
            out[(kk, bb)] = aa
    assert got == want


def test_float_accumulators_avoid_packed_transport():
    """Float accumulator sets route through the unpacked extract/scan paths
    (the packed path's float64 bitcast does not compile under TPU x64
    emulation — advisor r2 low) and still match the numpy oracle."""
    import numpy as np

    from arroyo_tpu.ops.aggregate import DeviceHashAggregator, ReadyHandle

    rng = np.random.default_rng(7)
    n = 5000
    keys = rng.integers(0, 50, n).astype(np.uint64)
    bins = rng.integers(0, 4, n).astype(np.int32)
    vals = rng.normal(size=n)

    kw = dict(cap=4096, batch_cap=1024, emit_cap=512)
    dev = DeviceHashAggregator(("sum", "min"), (np.float64, np.float64),
                               backend="jax", **kw)
    ora = DeviceHashAggregator(("sum", "min"), (np.float64, np.float64),
                               backend="numpy", **kw)
    assert not dev._packed_ok
    for a in (dev, ora):
        a.update(keys, bins, [vals, vals])

    h = dev.extract_start(0, 2, 2)
    assert isinstance(h, ReadyHandle) and h.is_ready()
    dk, db, daccs = h.result()
    ok, ob, oaccs = ora.extract(0, 2, 2)

    def table(k, b, accs):
        return {(int(kk), int(bb)): (float(a0), float(a1))
                for kk, bb, a0, a1 in zip(k, b, accs[0], accs[1])}

    dt, ot = table(dk, db, daccs), table(ok, ob, oaccs)
    assert set(dt) == set(ot)
    for kk in dt:
        np.testing.assert_allclose(dt[kk], ot[kk], rtol=1e-12)
    # non-destructive scan of the remaining bins also avoids the packed path
    dk2, db2, daccs2 = dev.scan_range(2, 4)
    ok2, ob2, oaccs2 = ora.scan_range(2, 4)
    dt2, ot2 = table(dk2, db2, daccs2), table(ok2, ob2, oaccs2)
    assert set(dt2) == set(ot2)
    for kk in dt2:
        np.testing.assert_allclose(dt2[kk], ot2[kk], rtol=1e-12)
