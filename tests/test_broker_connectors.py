"""From-scratch NATS and MQTT connectors against in-process mini-brokers
(the same fixture style the websocket/redis connectors use: the test
implements just enough of the broker protocol to exercise the client)."""

import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
from arroyo_tpu.expr import Col
from arroyo_tpu.graph import EdgeType, Graph, Node, OpName
from arroyo_tpu.engine import run_graph


# ------------------------------------------------------------- mini brokers


class MiniNats(threading.Thread):
    """Single-tenant core-NATS: INFO, CONNECT, PING/PONG, SUB, PUB->MSG."""

    def __init__(self):
        super().__init__(daemon=True)
        self.srv = socket.create_server(("127.0.0.1", 0))
        self.port = self.srv.getsockname()[1]
        self.subs = []  # (conn, subject, sid)
        self.published = []
        self._lock = threading.Lock()

    def run(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        conn.sendall(b'INFO {"server_id":"mini","version":"0"}\r\n')
        buf = b""
        try:
            while True:
                while b"\r\n" not in buf:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                line, buf = buf.split(b"\r\n", 1)
                if line.startswith(b"CONNECT"):
                    pass
                elif line == b"PING":
                    conn.sendall(b"PONG\r\n")
                elif line.startswith(b"SUB "):
                    _s, subject, sid = line.decode().split(" ")[:3]
                    with self._lock:
                        self.subs.append((conn, subject, sid))
                elif line.startswith(b"PUB "):
                    parts = line.decode().split(" ")
                    subject, n = parts[1], int(parts[-1])
                    while len(buf) < n + 2:
                        buf += conn.recv(65536)
                    payload, buf = buf[:n], buf[n + 2:]
                    with self._lock:
                        self.published.append((subject, payload))
                        for c, subj, sid in self.subs:
                            if subj == subject:
                                c.sendall(
                                    f"MSG {subject} {sid} {n}\r\n".encode()
                                    + payload + b"\r\n")
        except OSError:
            return

    def publish(self, subject: str, payload: bytes):
        with self._lock:
            for c, subj, sid in self.subs:
                if subj == subject:
                    c.sendall(f"MSG {subject} {sid} {len(payload)}\r\n".encode()
                              + payload + b"\r\n")

    def close(self):
        self.srv.close()


class MiniMqtt(threading.Thread):
    """Single-tenant MQTT 3.1.1 broker: CONNACK, SUBACK, PUBLISH routing,
    PUBACK for qos1 in both directions."""

    def __init__(self):
        super().__init__(daemon=True)
        self.srv = socket.create_server(("127.0.0.1", 0))
        self.port = self.srv.getsockname()[1]
        self.subs = []  # (conn, topic)
        self.published = []
        self._lock = threading.Lock()

    @staticmethod
    def _read_packet(conn, buf):
        def need(n):
            nonlocal buf
            while len(buf) < n:
                chunk = conn.recv(65536)
                if not chunk:
                    raise OSError("closed")
                buf += chunk
        need(1)
        h = buf[0]
        n, mult, i = 0, 1, 1
        while True:
            need(i + 1)
            d = buf[i]
            n += (d & 0x7F) * mult
            i += 1
            if not (d & 0x80):
                break
            mult *= 128
        need(i + n)
        body = buf[i:i + n]
        return h >> 4, h & 0x0F, body, buf[i + n:]

    def run(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        buf = b""
        try:
            while True:
                ptype, flags, body, buf = self._read_packet(conn, buf)
                if ptype == 1:  # CONNECT
                    conn.sendall(bytes([0x20, 2, 0, 0]))
                elif ptype == 8:  # SUBSCRIBE
                    pid = body[:2]
                    tlen = struct.unpack(">H", body[2:4])[0]
                    topic = body[4:4 + tlen].decode()
                    qos = body[4 + tlen]
                    with self._lock:
                        self.subs.append((conn, topic))
                    conn.sendall(bytes([0x90, 3]) + pid + bytes([qos]))
                elif ptype == 3:  # PUBLISH
                    tlen = struct.unpack(">H", body[:2])[0]
                    topic = body[2:2 + tlen].decode()
                    off = 2 + tlen
                    qos = (flags >> 1) & 3
                    if qos:
                        pid = body[off:off + 2]
                        off += 2
                        conn.sendall(bytes([0x40, 2]) + pid)
                    payload = body[off:]
                    with self._lock:
                        self.published.append((topic, payload))
                        for c, t in self.subs:
                            if t == topic and c is not conn:
                                var = struct.pack(">H", tlen) + topic.encode()
                                c.sendall(bytes([0x30]) +
                                          _mqtt_len(len(var) + len(payload)) +
                                          var + payload)
                elif ptype == 12:  # PINGREQ
                    conn.sendall(bytes([0xD0, 0]))
                elif ptype == 14:  # DISCONNECT
                    return
        except OSError:
            return

    def publish(self, topic: str, payload: bytes):
        var = struct.pack(">H", len(topic)) + topic.encode()
        with self._lock:
            for c, t in self.subs:
                if t == topic:
                    c.sendall(bytes([0x30]) + _mqtt_len(len(var) + len(payload))
                              + var + payload)

    def close(self):
        self.srv.close()


def _mqtt_len(n):
    out = bytearray()
    while True:
        d = n % 128
        n //= 128
        out.append(d | (0x80 if n else 0))
        if not n:
            return bytes(out)


# ------------------------------------------------------------------- tests


def _sink_graph(connector: str, conn_cfg: dict, count: int = 40):
    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "impulse", "message_count": count,
        "interval_micros": 1000, "start_time_micros": 0}, 1))
    g.add_node(Node("snk", OpName.SINK, {
        "connector": connector, "format": "json",
        "schema": Schema.of([("counter", "int64"), (TIMESTAMP_FIELD, "timestamp")]),
        **conn_cfg}, 1))
    g.add_edge("src", "snk", EdgeType.FORWARD, S)
    return g


def test_nats_sink_publishes(_storage):
    broker = MiniNats()
    broker.start()
    try:
        g = _sink_graph("nats", {"servers": f"nats://127.0.0.1:{broker.port}",
                                 "subject": "events"})
        run_graph(g, job_id="nats-sink", timeout=60)
        time.sleep(0.2)
        assert len(broker.published) == 40
        rows = [json.loads(p) for _s, p in broker.published]
        assert [r["counter"] for r in rows] == list(range(40))
    finally:
        broker.close()


def test_nats_source_roundtrip(_storage):
    broker = MiniNats()
    broker.start()
    rows: list = []
    S = Schema.of([("v", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "nats", "servers": f"nats://127.0.0.1:{broker.port}",
        "subject": "in", "format": "json",
        "schema": Schema.of([("v", "int64")])}, 1))
    g.add_node(Node("snk", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "snk", EdgeType.FORWARD, S)
    from arroyo_tpu.engine.engine import Engine

    eng = Engine(g, job_id="nats-src")
    eng.start()
    try:
        deadline = time.monotonic() + 20
        while not broker.subs and time.monotonic() < deadline:
            time.sleep(0.05)
        assert broker.subs, "source never subscribed"
        for i in range(25):
            broker.publish("in", json.dumps({"v": i}).encode())
        deadline = time.monotonic() + 30
        while sum(1 for _ in rows) < 25 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert sorted(r["v"] for r in rows) == list(range(25))
    finally:
        eng.stop()
        eng.join(timeout=30)
        broker.close()


def test_mqtt_sink_publishes_qos1(_storage):
    broker = MiniMqtt()
    broker.start()
    try:
        g = _sink_graph("mqtt", {"url": f"mqtt://127.0.0.1:{broker.port}",
                                 "topic": "t/events", "qos": 1})
        run_graph(g, job_id="mqtt-sink", timeout=60)
        time.sleep(0.2)
        assert len(broker.published) == 40
        rows = [json.loads(p) for _t, p in broker.published]
        assert [r["counter"] for r in rows] == list(range(40))
    finally:
        broker.close()


def test_mqtt_source_roundtrip(_storage):
    broker = MiniMqtt()
    broker.start()
    rows: list = []
    S = Schema.of([("v", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "mqtt", "url": f"mqtt://127.0.0.1:{broker.port}",
        "topic": "in", "format": "json",
        "schema": Schema.of([("v", "int64")])}, 1))
    g.add_node(Node("snk", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "snk", EdgeType.FORWARD, S)
    from arroyo_tpu.engine.engine import Engine

    eng = Engine(g, job_id="mqtt-src")
    eng.start()
    try:
        deadline = time.monotonic() + 20
        while not broker.subs and time.monotonic() < deadline:
            time.sleep(0.05)
        assert broker.subs, "source never subscribed"
        for i in range(25):
            broker.publish("in", json.dumps({"v": i}).encode())
        deadline = time.monotonic() + 30
        while sum(1 for _ in rows) < 25 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert sorted(r["v"] for r in rows) == list(range(25))
    finally:
        eng.stop()
        eng.join(timeout=30)
        broker.close()


class MiniKinesis(threading.Thread):
    """Single-stream Kinesis Data Streams over HTTP: ListShards,
    GetShardIterator (TRIM_HORIZON / LATEST / AFTER_SEQUENCE_NUMBER),
    GetRecords, PutRecords. Records land in 2 shards by hash of the
    partition key. Verifies requests carry a SigV4 Authorization header."""

    def __init__(self, n_shards=2):
        import base64
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        super().__init__(daemon=True)
        self.shards = {f"shardId-{i:012d}": [] for i in range(n_shards)}
        self.bad_auth = 0
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                target = self.headers.get("X-Amz-Target", "")
                auth = self.headers.get("Authorization", "")
                if not auth.startswith("AWS4-HMAC-SHA256"):
                    outer.bad_auth += 1
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                op = target.split(".")[-1]
                resp = getattr(outer, f"op_{op}")(body)
                data = json.dumps(resp).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        self._b64 = base64

    def run(self):
        self.httpd.serve_forever()

    def op_ListShards(self, body):
        return {"Shards": [{"ShardId": s} for s in sorted(self.shards)]}

    def op_GetShardIterator(self, body):
        shard = body["ShardId"]
        kind = body["ShardIteratorType"]
        if kind == "TRIM_HORIZON":
            idx = 0
        elif kind == "LATEST":
            idx = len(self.shards[shard])
        else:  # AFTER_SEQUENCE_NUMBER
            idx = int(body["StartingSequenceNumber"].split("-")[-1]) + 1
        return {"ShardIterator": f"{shard}|{idx}"}

    def op_GetRecords(self, body):
        shard, idx = body["ShardIterator"].split("|")
        idx = int(idx)
        recs = self.shards[shard][idx:idx + int(body.get("Limit", 1000))]
        out = [{"Data": d, "SequenceNumber": f"{shard}-{idx + i}",
                "ApproximateArrivalTimestamp": time.time()}
               for i, d in enumerate(recs)]
        return {"Records": out,
                "NextShardIterator": f"{shard}|{idx + len(recs)}"}

    def op_PutRecords(self, body):
        for r in body["Records"]:
            shard = sorted(self.shards)[hash(r["PartitionKey"]) % len(self.shards)]
            self.shards[shard].append(r["Data"])
        return {"FailedRecordCount": 0, "Records": []}

    def put(self, payload: bytes, shard=None):
        s = shard or sorted(self.shards)[0]
        self.shards[s].append(self._b64.b64encode(payload).decode())

    def all_payloads(self):
        return [self._b64.b64decode(d)
                for s in sorted(self.shards) for d in self.shards[s]]

    def close(self):
        self.httpd.shutdown()


def test_kinesis_sink_and_source_roundtrip(_storage):
    srv = MiniKinesis()
    srv.start()
    try:
        # sink: 40 impulse rows -> PutRecords across shards, SigV4-signed
        g = _sink_graph("kinesis", {
            "stream_name": "s1", "endpoint": f"http://127.0.0.1:{srv.port}",
            "aws_access_key_id": "AK", "aws_secret_access_key": "SK"})
        run_graph(g, job_id="kin-sink", timeout=60)
        rows = [json.loads(p) for p in srv.all_payloads()]
        assert sorted(r["counter"] for r in rows) == list(range(40))
        assert srv.bad_auth == 0

        # source: read everything back from TRIM_HORIZON
        out: list = []
        S = Schema.of([("counter", "int64"), (TIMESTAMP_FIELD, "int64")])
        g2 = Graph()
        g2.add_node(Node("src", OpName.SOURCE, {
            "connector": "kinesis", "stream_name": "s1",
            "endpoint": f"http://127.0.0.1:{srv.port}",
            "aws_access_key_id": "AK", "aws_secret_access_key": "SK",
            "format": "json", "poll_interval_s": 0.05,
            "schema": Schema.of([("counter", "int64")])}, 1))
        g2.add_node(Node("snk", OpName.SINK, {"connector": "vec", "rows": out}, 1))
        g2.add_edge("src", "snk", EdgeType.FORWARD, S)
        from arroyo_tpu.engine.engine import Engine

        eng = Engine(g2, job_id="kin-src")
        eng.start()
        try:
            deadline = time.monotonic() + 30
            while len(out) < 40 and time.monotonic() < deadline:
                time.sleep(0.1)
            assert sorted(r["counter"] for r in out) == list(range(40))
        finally:
            eng.stop()
            eng.join(timeout=30)
    finally:
        srv.close()


class MiniRabbit(threading.Thread):
    """Single-vhost AMQP 0-9-1 broker: PLAIN handshake, channel 1,
    Queue.Declare, Basic.Publish routing to queues, Basic.Consume with
    round-robin-of-one delivery, Basic.Ack bookkeeping, heartbeats."""

    FRAME_END = 0xCE

    def __init__(self):
        super().__init__(daemon=True)
        self.srv = socket.create_server(("127.0.0.1", 0))
        self.port = self.srv.getsockname()[1]
        self.queues: dict = {}
        self.acked: list = []
        self.consumers: list = []  # (conn, queue)
        self._lock = threading.Lock()
        self._tag = 0

    @staticmethod
    def _shortstr(s):
        b = s.encode()
        return struct.pack(">B", len(b)) + b

    def _frame(self, conn, ftype, channel, payload):
        conn.sendall(struct.pack(">BHI", ftype, channel, len(payload))
                     + payload + bytes([self.FRAME_END]))

    def _method(self, conn, channel, cid, mid, args=b""):
        self._frame(conn, 1, channel, struct.pack(">HH", cid, mid) + args)

    def run(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _read_frame(self, conn, buf):
        while len(buf) < 7:
            chunk = conn.recv(65536)
            if not chunk:
                raise OSError("closed")
            buf += chunk
        ftype, ch, size = struct.unpack(">BHI", buf[:7])
        while len(buf) < 7 + size + 1:
            buf += conn.recv(65536)
        return ftype, ch, buf[7:7 + size], buf[7 + size + 1:]

    def _serve(self, conn):
        try:
            buf = b""
            while len(buf) < 8:
                buf += conn.recv(8)
            assert buf[:8] == b"AMQP\x00\x00\x09\x01"
            buf = buf[8:]
            self._method(conn, 0, 10, 10, struct.pack(">BB", 0, 9)
                         + struct.pack(">I", 0) + struct.pack(">I", 5) + b"PLAIN"
                         + struct.pack(">I", 5) + b"en_US")
            pending_pub = None
            while True:
                ftype, ch, payload, buf = self._read_frame(conn, buf)
                if ftype == 8:
                    self._frame(conn, 8, 0, b"")
                    continue
                if ftype == 2 and pending_pub is not None:
                    (_cls, _w, size) = struct.unpack(">HHQ", payload[:12])
                    pending_pub = (pending_pub[0], size, b"")
                    if size == 0:
                        self._publish(pending_pub[0], b"")
                        pending_pub = None
                    continue
                if ftype == 3 and pending_pub is not None:
                    rk, size, body = pending_pub
                    body += payload
                    if len(body) >= size:
                        self._publish(rk, body)
                        pending_pub = None
                    else:
                        pending_pub = (rk, size, body)
                    continue
                if ftype != 1:
                    continue
                cid, mid = struct.unpack(">HH", payload[:4])
                args = payload[4:]
                if (cid, mid) == (10, 11):   # Start-Ok
                    self._method(conn, 0, 10, 30, struct.pack(">HIH", 0, 131072, 0))
                elif (cid, mid) == (10, 31):  # Tune-Ok
                    pass
                elif (cid, mid) == (10, 40):  # Open
                    self._method(conn, 0, 10, 41, self._shortstr(""))
                elif (cid, mid) == (20, 10):  # Channel.Open
                    self._method(conn, ch, 20, 11, struct.pack(">I", 0))
                elif (cid, mid) == (50, 10):  # Queue.Declare
                    qlen = args[2]
                    q = args[3:3 + qlen].decode()
                    with self._lock:
                        self.queues.setdefault(q, [])
                    self._method(conn, ch, 50, 11, self._shortstr(q)
                                 + struct.pack(">II", 0, 0))
                elif (cid, mid) == (60, 20):  # Basic.Consume
                    qlen = args[2]
                    q = args[3:3 + qlen].decode()
                    with self._lock:
                        self.consumers.append((conn, q))
                        backlog = list(self.queues.get(q, []))
                        self.queues[q] = []
                    self._method(conn, ch, 60, 21, self._shortstr("ctag"))
                    for body in backlog:
                        self._deliver(conn, body)
                elif (cid, mid) == (60, 40):  # Basic.Publish
                    off = 2
                    exlen = args[off]
                    off += 1 + exlen
                    rklen = args[off]
                    rk = args[off + 1:off + 1 + rklen].decode()
                    pending_pub = (rk, None, b"")
                elif (cid, mid) == (60, 80):  # Basic.Ack
                    (tag,) = struct.unpack(">Q", args[:8])
                    with self._lock:
                        self.acked.append(tag)
        except (OSError, AssertionError):
            return

    def _publish(self, rk, body):
        with self._lock:
            for conn, q in self.consumers:
                if q == rk:
                    self._deliver(conn, body)
                    return
            self.queues.setdefault(rk, []).append(body)

    def _deliver(self, conn, body):
        self._tag += 1
        args = (self._shortstr("ctag") + struct.pack(">Q", self._tag) + b"\x00"
                + self._shortstr("") + self._shortstr("q"))
        self._method(conn, 1, 60, 60, args)
        self._frame(conn, 2, 1, struct.pack(">HHQH", 60, 0, len(body), 0))
        if body:
            self._frame(conn, 3, 1, body)

    def publish(self, queue, body):
        self._publish(queue, body)

    def close(self):
        self.srv.close()


def test_rabbitmq_sink_publishes(_storage):
    broker = MiniRabbit()
    broker.start()
    try:
        g = _sink_graph("rabbitmq", {
            "host": "127.0.0.1", "port": broker.port, "queue": "events"})
        run_graph(g, job_id="rmq-sink", timeout=60)
        time.sleep(0.3)
        msgs = broker.queues.get("events", [])
        rows = [json.loads(p) for p in msgs]
        assert [r["counter"] for r in rows] == list(range(40))
    finally:
        broker.close()


def test_rabbitmq_source_roundtrip(_storage):
    broker = MiniRabbit()
    broker.start()
    rows: list = []
    S = Schema.of([("v", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "rabbitmq", "host": "127.0.0.1", "port": broker.port,
        "queue": "in", "format": "json",
        "schema": Schema.of([("v", "int64")])}, 1))
    g.add_node(Node("snk", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "snk", EdgeType.FORWARD, S)
    from arroyo_tpu.engine.engine import Engine

    eng = Engine(g, job_id="rmq-src")
    eng.start()
    try:
        deadline = time.monotonic() + 20
        while not broker.consumers and time.monotonic() < deadline:
            time.sleep(0.05)
        assert broker.consumers, "source never consumed"
        for i in range(25):
            broker.publish("in", json.dumps({"v": i}).encode())
        deadline = time.monotonic() + 30
        while len(rows) < 25 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert sorted(r["v"] for r in rows) == list(range(25))
        # at-least-once: acks DEFER until a checkpoint covers the messages
        # (a crash before the barrier leaves them unacked for redelivery)
        assert len(broker.acked) == 0
        assert eng.checkpoint_and_wait(1, timeout=30)
        deadline = time.monotonic() + 10
        while len(broker.acked) < 25 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(broker.acked) == 25
    finally:
        eng.stop()
        eng.join(timeout=30)
        broker.close()


def test_delta_sink_writes_table(tmp_path, _storage):
    """Delta sink: parquet parts + transaction log with protocol/metaData on
    version 0 and add actions per commit; pyarrow can read the parts the
    log references and row counts are exact."""
    import glob
    import os

    out = str(tmp_path / "dtable")
    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "impulse", "message_count": 60,
        "interval_micros": 1000, "start_time_micros": 0}, 1))
    g.add_node(Node("snk", OpName.SINK, {
        "connector": "delta", "path": out,
        "partition_fields": ["p"],
        "schema": Schema.of([("counter", "int64"), ("p", "int64")])}, 1))
    g.add_node(Node("val", OpName.VALUE, {
        "projections": [("counter", Col("counter")),
                        ("p", __import__("arroyo_tpu.expr", fromlist=["BinOp"]).BinOp(
                            "%", Col("counter"), __import__("arroyo_tpu.expr", fromlist=["Lit"]).Lit(2)))]}, 1))
    g.add_edge("src", "val", EdgeType.FORWARD, S)
    g.add_edge("val", "snk", EdgeType.FORWARD, S)
    run_graph(g, job_id="delta-sink", timeout=60)

    log = sorted(glob.glob(os.path.join(out, "_delta_log", "*.json")))
    assert log, "no delta log written"
    actions = [json.loads(l) for l in open(log[0]) if l.strip()]
    kinds = [next(iter(a)) for a in actions]
    assert kinds[0] == "protocol" and kinds[1] == "metaData"
    meta = actions[1]["metaData"]
    assert meta["partitionColumns"] == ["p"]
    schema_fields = {f["name"]: f["type"]
                     for f in json.loads(meta["schemaString"])["fields"]}
    assert schema_fields == {"counter": "long", "p": "long"}
    adds = [a["add"] for l in log for a in
            (json.loads(x) for x in open(l) if x.strip()) if "add" in a]
    assert adds
    import pyarrow.parquet as pq

    total = 0
    for a in adds:
        t = pq.read_table(os.path.join(out, a["path"]))
        total += t.num_rows
        assert "counter" in t.column_names
    assert total == 60
    assert {a["partitionValues"]["p"] for a in adds} == {"0", "1"}
