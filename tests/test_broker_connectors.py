"""From-scratch NATS and MQTT connectors against in-process mini-brokers
(the same fixture style the websocket/redis connectors use: the test
implements just enough of the broker protocol to exercise the client)."""

import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
from arroyo_tpu.expr import Col
from arroyo_tpu.graph import EdgeType, Graph, Node, OpName
from arroyo_tpu.engine import run_graph


# ------------------------------------------------------------- mini brokers


class MiniNats(threading.Thread):
    """Single-tenant core-NATS: INFO, CONNECT, PING/PONG, SUB, PUB->MSG."""

    def __init__(self):
        super().__init__(daemon=True)
        self.srv = socket.create_server(("127.0.0.1", 0))
        self.port = self.srv.getsockname()[1]
        self.subs = []  # (conn, subject, sid)
        self.published = []
        self._lock = threading.Lock()

    def run(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        conn.sendall(b'INFO {"server_id":"mini","version":"0"}\r\n')
        buf = b""
        try:
            while True:
                while b"\r\n" not in buf:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                line, buf = buf.split(b"\r\n", 1)
                if line.startswith(b"CONNECT"):
                    pass
                elif line == b"PING":
                    conn.sendall(b"PONG\r\n")
                elif line.startswith(b"SUB "):
                    _s, subject, sid = line.decode().split(" ")[:3]
                    with self._lock:
                        self.subs.append((conn, subject, sid))
                elif line.startswith(b"PUB "):
                    parts = line.decode().split(" ")
                    subject, n = parts[1], int(parts[-1])
                    while len(buf) < n + 2:
                        buf += conn.recv(65536)
                    payload, buf = buf[:n], buf[n + 2:]
                    with self._lock:
                        self.published.append((subject, payload))
                        for c, subj, sid in self.subs:
                            if subj == subject:
                                c.sendall(
                                    f"MSG {subject} {sid} {n}\r\n".encode()
                                    + payload + b"\r\n")
        except OSError:
            return

    def publish(self, subject: str, payload: bytes):
        with self._lock:
            for c, subj, sid in self.subs:
                if subj == subject:
                    c.sendall(f"MSG {subject} {sid} {len(payload)}\r\n".encode()
                              + payload + b"\r\n")

    def close(self):
        self.srv.close()


class MiniMqtt(threading.Thread):
    """Single-tenant MQTT 3.1.1 broker: CONNACK, SUBACK, PUBLISH routing,
    PUBACK for qos1 in both directions."""

    def __init__(self):
        super().__init__(daemon=True)
        self.srv = socket.create_server(("127.0.0.1", 0))
        self.port = self.srv.getsockname()[1]
        self.subs = []  # (conn, topic)
        self.published = []
        self._lock = threading.Lock()

    @staticmethod
    def _read_packet(conn, buf):
        def need(n):
            nonlocal buf
            while len(buf) < n:
                chunk = conn.recv(65536)
                if not chunk:
                    raise OSError("closed")
                buf += chunk
        need(1)
        h = buf[0]
        n, mult, i = 0, 1, 1
        while True:
            need(i + 1)
            d = buf[i]
            n += (d & 0x7F) * mult
            i += 1
            if not (d & 0x80):
                break
            mult *= 128
        need(i + n)
        body = buf[i:i + n]
        return h >> 4, h & 0x0F, body, buf[i + n:]

    def run(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        buf = b""
        try:
            while True:
                ptype, flags, body, buf = self._read_packet(conn, buf)
                if ptype == 1:  # CONNECT
                    conn.sendall(bytes([0x20, 2, 0, 0]))
                elif ptype == 8:  # SUBSCRIBE
                    pid = body[:2]
                    tlen = struct.unpack(">H", body[2:4])[0]
                    topic = body[4:4 + tlen].decode()
                    qos = body[4 + tlen]
                    with self._lock:
                        self.subs.append((conn, topic))
                    conn.sendall(bytes([0x90, 3]) + pid + bytes([qos]))
                elif ptype == 3:  # PUBLISH
                    tlen = struct.unpack(">H", body[:2])[0]
                    topic = body[2:2 + tlen].decode()
                    off = 2 + tlen
                    qos = (flags >> 1) & 3
                    if qos:
                        pid = body[off:off + 2]
                        off += 2
                        conn.sendall(bytes([0x40, 2]) + pid)
                    payload = body[off:]
                    with self._lock:
                        self.published.append((topic, payload))
                        for c, t in self.subs:
                            if t == topic and c is not conn:
                                var = struct.pack(">H", tlen) + topic.encode()
                                c.sendall(bytes([0x30]) +
                                          _mqtt_len(len(var) + len(payload)) +
                                          var + payload)
                elif ptype == 12:  # PINGREQ
                    conn.sendall(bytes([0xD0, 0]))
                elif ptype == 14:  # DISCONNECT
                    return
        except OSError:
            return

    def publish(self, topic: str, payload: bytes):
        var = struct.pack(">H", len(topic)) + topic.encode()
        with self._lock:
            for c, t in self.subs:
                if t == topic:
                    c.sendall(bytes([0x30]) + _mqtt_len(len(var) + len(payload))
                              + var + payload)

    def close(self):
        self.srv.close()


def _mqtt_len(n):
    out = bytearray()
    while True:
        d = n % 128
        n //= 128
        out.append(d | (0x80 if n else 0))
        if not n:
            return bytes(out)


# ------------------------------------------------------------------- tests


def _sink_graph(connector: str, conn_cfg: dict, count: int = 40):
    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "impulse", "message_count": count,
        "interval_micros": 1000, "start_time_micros": 0}, 1))
    g.add_node(Node("snk", OpName.SINK, {
        "connector": connector, "format": "json",
        "schema": Schema.of([("counter", "int64"), (TIMESTAMP_FIELD, "timestamp")]),
        **conn_cfg}, 1))
    g.add_edge("src", "snk", EdgeType.FORWARD, S)
    return g


def test_nats_sink_publishes(_storage):
    broker = MiniNats()
    broker.start()
    try:
        g = _sink_graph("nats", {"servers": f"nats://127.0.0.1:{broker.port}",
                                 "subject": "events"})
        run_graph(g, job_id="nats-sink", timeout=60)
        time.sleep(0.2)
        assert len(broker.published) == 40
        rows = [json.loads(p) for _s, p in broker.published]
        assert [r["counter"] for r in rows] == list(range(40))
    finally:
        broker.close()


def test_nats_source_roundtrip(_storage):
    broker = MiniNats()
    broker.start()
    rows: list = []
    S = Schema.of([("v", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "nats", "servers": f"nats://127.0.0.1:{broker.port}",
        "subject": "in", "format": "json",
        "schema": Schema.of([("v", "int64")])}, 1))
    g.add_node(Node("snk", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "snk", EdgeType.FORWARD, S)
    from arroyo_tpu.engine.engine import Engine

    eng = Engine(g, job_id="nats-src")
    eng.start()
    try:
        deadline = time.monotonic() + 20
        while not broker.subs and time.monotonic() < deadline:
            time.sleep(0.05)
        assert broker.subs, "source never subscribed"
        for i in range(25):
            broker.publish("in", json.dumps({"v": i}).encode())
        deadline = time.monotonic() + 30
        while sum(1 for _ in rows) < 25 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert sorted(r["v"] for r in rows) == list(range(25))
    finally:
        eng.stop()
        eng.join(timeout=30)
        broker.close()


def test_mqtt_sink_publishes_qos1(_storage):
    broker = MiniMqtt()
    broker.start()
    try:
        g = _sink_graph("mqtt", {"url": f"mqtt://127.0.0.1:{broker.port}",
                                 "topic": "t/events", "qos": 1})
        run_graph(g, job_id="mqtt-sink", timeout=60)
        time.sleep(0.2)
        assert len(broker.published) == 40
        rows = [json.loads(p) for _t, p in broker.published]
        assert [r["counter"] for r in rows] == list(range(40))
    finally:
        broker.close()


def test_mqtt_source_roundtrip(_storage):
    broker = MiniMqtt()
    broker.start()
    rows: list = []
    S = Schema.of([("v", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "mqtt", "url": f"mqtt://127.0.0.1:{broker.port}",
        "topic": "in", "format": "json",
        "schema": Schema.of([("v", "int64")])}, 1))
    g.add_node(Node("snk", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "snk", EdgeType.FORWARD, S)
    from arroyo_tpu.engine.engine import Engine

    eng = Engine(g, job_id="mqtt-src")
    eng.start()
    try:
        deadline = time.monotonic() + 20
        while not broker.subs and time.monotonic() < deadline:
            time.sleep(0.05)
        assert broker.subs, "source never subscribed"
        for i in range(25):
            broker.publish("in", json.dumps({"v": i}).encode())
        deadline = time.monotonic() + 30
        while sum(1 for _ in rows) < 25 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert sorted(r["v"] for r in rows) == list(range(25))
    finally:
        eng.stop()
        eng.join(timeout=30)
        broker.close()


def test_delta_sink_writes_table(tmp_path, _storage):
    """Delta sink: parquet parts + transaction log with protocol/metaData on
    version 0 and add actions per commit; pyarrow can read the parts the
    log references and row counts are exact."""
    import glob
    import os

    out = str(tmp_path / "dtable")
    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "impulse", "message_count": 60,
        "interval_micros": 1000, "start_time_micros": 0}, 1))
    g.add_node(Node("snk", OpName.SINK, {
        "connector": "delta", "path": out,
        "partition_fields": ["p"],
        "schema": Schema.of([("counter", "int64"), ("p", "int64")])}, 1))
    g.add_node(Node("val", OpName.VALUE, {
        "projections": [("counter", Col("counter")),
                        ("p", __import__("arroyo_tpu.expr", fromlist=["BinOp"]).BinOp(
                            "%", Col("counter"), __import__("arroyo_tpu.expr", fromlist=["Lit"]).Lit(2)))]}, 1))
    g.add_edge("src", "val", EdgeType.FORWARD, S)
    g.add_edge("val", "snk", EdgeType.FORWARD, S)
    run_graph(g, job_id="delta-sink", timeout=60)

    log = sorted(glob.glob(os.path.join(out, "_delta_log", "*.json")))
    assert log, "no delta log written"
    actions = [json.loads(l) for l in open(log[0]) if l.strip()]
    kinds = [next(iter(a)) for a in actions]
    assert kinds[0] == "protocol" and kinds[1] == "metaData"
    meta = actions[1]["metaData"]
    assert meta["partitionColumns"] == ["p"]
    schema_fields = {f["name"]: f["type"]
                     for f in json.loads(meta["schemaString"])["fields"]}
    assert schema_fields == {"counter": "long", "p": "long"}
    adds = [a["add"] for l in log for a in
            (json.loads(x) for x in open(l) if x.strip()) if "add" in a]
    assert adds
    import pyarrow.parquet as pq

    total = 0
    for a in adds:
        t = pq.read_table(os.path.join(out, a["path"]))
        total += t.num_rows
        assert "counter" in t.column_names
    assert total == 60
    assert {a["partitionValues"]["p"] for a in adds} == {"0", "1"}
