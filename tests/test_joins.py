"""Join operators: windowed instant join (inner/left/right/full), updating
join with retractions, lookup join caching."""

import numpy as np
import pytest

from arroyo_tpu.batch import Batch, TIMESTAMP_FIELD
from arroyo_tpu.hashing import hash_columns
from arroyo_tpu.operators.base import OperatorContext
from arroyo_tpu.operators.joins import InstantJoin, JoinWithExpiration, LookupJoin
from arroyo_tpu.operators.updating_aggregate import IS_RETRACT_FIELD, merge_updating_rows
from arroyo_tpu.state.tables import TableManager
from arroyo_tpu.types import TaskInfo, Watermark


class FakeCollector:
    def __init__(self):
        self.batches = []

    def collect(self, b):
        self.batches.append(b)

    def broadcast(self, s):
        pass


def rows_of(col):
    out = []
    for b in col.batches:
        out.extend(b.to_pylist())
    return out


def two_input_ctx(name="join", storage="/tmp/join-unused"):
    """Context where input 0 -> edge 0 (left), input 1 -> edge 1 (right)."""
    ti = TaskInfo("j", name, name, 0, 1)
    return OperatorContext(
        ti, None, TableManager(ti, storage), in_edge_of_input=lambda i: (i, 0)
    )


def kb(ts, keys, vals, vname="v", retracts=None):
    k = np.array(keys, dtype=np.int64)
    cols = {
        TIMESTAMP_FIELD: np.array(ts, dtype=np.int64),
        "id": k,
        vname: np.array(vals, dtype=object),
        "_key": hash_columns([k]),
    }
    if retracts is not None:
        cols[IS_RETRACT_FIELD] = np.array(retracts, dtype=bool)
    return Batch(cols)


def make_instant(jt="inner"):
    op = InstantJoin({
        "join_type": jt,
        "left_names": [("lid", "id"), ("lv", "v")],
        "right_names": [("rid", "id"), ("rv", "v")],
    })
    return op, two_input_ctx(), FakeCollector()


def test_instant_inner_join():
    op, ctx, col = make_instant()
    op.process_batch(kb([100, 100], [1, 2], ["a", "b"]), ctx, col, input_index=0)
    op.process_batch(kb([100, 100, 100], [2, 2, 3], ["x", "y", "z"]), ctx, col, input_index=1)
    op.handle_watermark(Watermark.event_time(50), ctx, col)
    assert rows_of(col) == []  # bucket 100 not closed yet
    op.handle_watermark(Watermark.event_time(101), ctx, col)
    rows = sorted(rows_of(col), key=lambda r: (r["lid"], r["rv"]))
    assert [(r["lid"], r["lv"], r["rid"], r["rv"]) for r in rows] == [
        (2, "b", 2, "x"), (2, "b", 2, "y"),
    ]


def test_instant_outer_joins():
    for jt, expected in [
        ("left", {(1, "a", None, None), (2, "b", 2, "x")}),
        ("right", {(2, "b", 2, "x"), (None, None, 3, "z")}),
        ("full", {(1, "a", None, None), (2, "b", 2, "x"), (None, None, 3, "z")}),
    ]:
        op, ctx, col = make_instant(jt)
        op.process_batch(kb([100, 100], [1, 2], ["a", "b"]), ctx, col, input_index=0)
        op.process_batch(kb([100, 100], [2, 3], ["x", "z"]), ctx, col, input_index=1)
        op.on_close(ctx, col)
        got = {(r["lid"], r["lv"], r["rid"], r["rv"]) for r in rows_of(col)}
        assert got == expected, jt


def test_instant_join_buckets_by_timestamp():
    """Rows in different time buckets never join."""
    op, ctx, col = make_instant()
    op.process_batch(kb([100], [1], ["a"]), ctx, col, input_index=0)
    op.process_batch(kb([200], [1], ["b"]), ctx, col, input_index=1)
    op.on_close(ctx, col)
    assert rows_of(col) == []


def test_instant_join_checkpoint_restore(tmp_path):
    storage = str(tmp_path / "ij")
    cfg = {
        "join_type": "inner",
        "left_names": [("lid", "id"), ("lv", "v")],
        "right_names": [("rid", "id"), ("rv", "v")],
    }
    ti = TaskInfo("j", "join", "instant_join", 0, 1)
    tm = TableManager(ti, storage)
    ctx = OperatorContext(ti, None, tm, in_edge_of_input=lambda i: (i, 0))
    op = InstantJoin(cfg)
    col = FakeCollector()
    op.process_batch(kb([100], [1], ["a"]), ctx, col, input_index=0)
    op.handle_checkpoint(None, ctx, col)
    tm.checkpoint(1, None)

    op2 = InstantJoin(cfg)
    tm2 = TableManager(ti, storage)
    tm2.restore(1, op2.tables())
    ctx2 = OperatorContext(ti, None, tm2, in_edge_of_input=lambda i: (i, 0))
    col2 = FakeCollector()
    op2.on_start(ctx2)
    op2.process_batch(kb([100], [1], ["z"]), ctx2, col2, input_index=1)
    op2.on_close(ctx2, col2)
    rows = rows_of(col2)
    assert len(rows) == 1 and rows[0]["lv"] == "a" and rows[0]["rv"] == "z"


# ---------------------------------------------------------------- updating


def make_updating(jt="inner"):
    op = JoinWithExpiration({
        "join_type": jt,
        "left_names": [("lid", "id"), ("lv", "v")],
        "right_names": [("rid", "id"), ("rv", "v")],
    })
    return op, two_input_ctx("exp_join"), FakeCollector()


def test_updating_inner_join_append_only():
    op, ctx, col = make_updating()
    op.process_batch(kb([0], [1], ["a"]), ctx, col, input_index=0)
    assert rows_of(col) == []  # no match yet
    op.process_batch(kb([1], [1], ["x"]), ctx, col, input_index=1)
    rows = rows_of(col)
    assert len(rows) == 1
    assert rows[0]["lv"] == "a" and rows[0]["rv"] == "x"
    assert rows[0][IS_RETRACT_FIELD] is False
    # second left row joins existing right
    op.process_batch(kb([2], [1], ["b"]), ctx, col, input_index=0)
    final = merge_updating_rows(rows_of(col))
    assert len(final) == 2


def test_updating_left_join_null_then_match():
    op, ctx, col = make_updating("left")
    op.process_batch(kb([0], [1], ["a"]), ctx, col, input_index=0)
    rows = rows_of(col)
    # immediate (left, null) emission
    assert len(rows) == 1 and rows[0]["rv"] is None and not rows[0][IS_RETRACT_FIELD]
    op.process_batch(kb([1], [1], ["x"]), ctx, col, input_index=1)
    rows = rows_of(col)
    # nulls retracted, matched pair appended
    assert len(rows) == 3
    assert rows[1][IS_RETRACT_FIELD] is True and rows[1]["rv"] is None
    assert rows[2][IS_RETRACT_FIELD] is False and rows[2]["rv"] == "x"
    final = merge_updating_rows(rows)
    assert final == [{"lid": 1, "lv": "a", "rid": 1, "rv": "x"}]


def test_updating_join_retract_last_match_restores_nulls():
    op, ctx, col = make_updating("left")
    op.process_batch(kb([0], [1], ["a"]), ctx, col, input_index=0)
    op.process_batch(kb([1], [1], ["x"]), ctx, col, input_index=1)
    # retract the right row: pair retracted, (left, null) re-emitted
    op.process_batch(kb([2], [1], ["x"], retracts=[True]), ctx, col, input_index=1)
    final = merge_updating_rows(rows_of(col))
    assert final == [{"lid": 1, "lv": "a", "rid": None, "rv": None}]


def test_updating_full_join():
    op, ctx, col = make_updating("full")
    op.process_batch(kb([0], [1], ["a"]), ctx, col, input_index=0)
    op.process_batch(kb([1], [2], ["x"]), ctx, col, input_index=1)
    final = sorted(
        merge_updating_rows(rows_of(col)),
        key=lambda r: (r["lid"] is None, r["lid"] or 0),
    )
    assert final == [
        {"lid": 1, "lv": "a", "rid": None, "rv": None},
        {"lid": None, "lv": None, "rid": 2, "rv": "x"},
    ]


def test_updating_join_ttl_expiry():
    op, ctx, col = make_updating()
    op.ttl = 1000
    op.process_batch(kb([0], [1], ["a"]), ctx, col, input_index=0)
    op.handle_watermark(Watermark.event_time(5000), ctx, col)  # expire left row
    op.process_batch(kb([5000], [1], ["x"]), ctx, col, input_index=1)
    assert rows_of(col) == []  # expired row no longer joins


def test_updating_join_checkpoint_restore(tmp_path):
    storage = str(tmp_path / "uj")
    cfg = {
        "join_type": "left",
        "left_names": [("lid", "id"), ("lv", "v")],
        "right_names": [("rid", "id"), ("rv", "v")],
    }
    ti = TaskInfo("j", "exp_join", "join_with_expiration", 0, 1)
    tm = TableManager(ti, storage)
    ctx = OperatorContext(ti, None, tm, in_edge_of_input=lambda i: (i, 0))
    op = JoinWithExpiration(cfg)
    col = FakeCollector()
    op.process_batch(kb([0], [1], ["a"]), ctx, col, input_index=0)  # emits (a, null)
    op.handle_checkpoint(None, ctx, col)
    tm.checkpoint(1, None)

    op2 = JoinWithExpiration(cfg)
    tm2 = TableManager(ti, storage)
    tm2.restore(1, op2.tables())
    ctx2 = OperatorContext(ti, None, tm2, in_edge_of_input=lambda i: (i, 0))
    col2 = FakeCollector()
    op2.on_start(ctx2)
    op2.process_batch(kb([1], [1], ["x"]), ctx2, col2, input_index=1)
    rows = rows_of(col2)
    # null_emitted survived the restore: nulls retracted before the append
    assert len(rows) == 2
    assert rows[0][IS_RETRACT_FIELD] is True and rows[0]["rv"] is None
    assert rows[1][IS_RETRACT_FIELD] is False and rows[1]["rv"] == "x"


# ---------------------------------------------------------------- lookup


class DictLookup:
    def __init__(self, table):
        self.table = table
        self.calls = 0

    def lookup(self, keys):
        self.calls += 1
        return {k: self.table.get(k) for k in keys}


def _lookup_drain(op, ctx, col):
    """Async lookups emit in order; a barrier force-drains everything
    (watermarks queue behind batches instead of blocking)."""
    op.handle_checkpoint(None, ctx, col)


def test_lookup_join_left_and_cache():
    conn = DictLookup({1: {"name": "one"}, 2: {"name": "two"}})
    from arroyo_tpu.expr import Col

    op = LookupJoin({
        "connector": conn,
        "key_exprs": [Col("id")],
        "right_names": [("name", "name")],
        "join_type": "left",
    })
    ctx = two_input_ctx("lookup")
    col = FakeCollector()
    op.process_batch(kb([0, 1, 2], [1, 2, 9], ["a", "b", "c"]), ctx, col)
    _lookup_drain(op, ctx, col)
    rows = rows_of(col)
    assert [r["name"] for r in rows] == ["one", "two", None]
    assert conn.calls == 1
    op.process_batch(kb([3], [1], ["d"]), ctx, col)
    _lookup_drain(op, ctx, col)
    assert conn.calls == 1  # cache hit


def test_lookup_join_inner_filters_missing():
    conn = DictLookup({1: {"name": "one"}})
    from arroyo_tpu.expr import Col

    op = LookupJoin({
        "connector": conn,
        "key_exprs": [Col("id")],
        "right_names": [("name", "name")],
        "join_type": "inner",
    })
    ctx = two_input_ctx("lookup")
    col = FakeCollector()
    op.process_batch(kb([0, 1], [1, 9], ["a", "b"]), ctx, col)
    _lookup_drain(op, ctx, col)
    rows = rows_of(col)
    assert len(rows) == 1 and rows[0]["v"] == "a" and rows[0]["name"] == "one"


def test_lookup_join_watermark_rides_pending_queue():
    """A watermark arriving while fetches are in flight must broadcast
    AFTER the batches that preceded it, without blocking the task thread
    for the whole fetch latency."""
    import time

    from arroyo_tpu.expr import Col
    from arroyo_tpu.types import SignalKind, Watermark

    class SlowLookup:
        def lookup(self, keys):
            time.sleep(0.05)
            return {k: {"name": f"n{k}"} for k in keys}

    class OrderCollector(FakeCollector):
        def __init__(self):
            super().__init__()
            self.events = []

        def collect(self, b):
            super().collect(b)
            self.events.append("batch")

        def broadcast(self, s):
            if s.kind == SignalKind.WATERMARK:
                self.events.append("wm")

    op = LookupJoin({
        "connector": SlowLookup(),
        "key_exprs": [Col("id")],
        "right_names": [("name", "name")],
        "join_type": "left",
    })
    ctx = two_input_ctx("lookup")
    col = OrderCollector()
    t0 = time.perf_counter()
    op.process_batch(kb([0], [1], ["a"]), ctx, col)
    out = op.handle_watermark(Watermark.event_time(10), ctx, col)
    queued_fast = time.perf_counter() - t0 < 0.04  # did not block on the fetch
    assert out is None and queued_fast  # held behind the in-flight batch
    op.process_batch(kb([1], [2], ["b"]), ctx, col)
    _lookup_drain(op, ctx, col)
    assert col.events == ["batch", "wm", "batch"]


def test_lookup_join_async_sustains_slow_source():
    """A 50ms-latency lookup source must overlap fetches across batches
    (VERDICT r4 weak #4): 12 batches of all-new keys would serialize to
    ~600ms; the pipelined path must land well under half that while
    preserving input order and exact results."""
    import time

    class SlowLookup:
        def __init__(self):
            self.calls = 0

        def lookup(self, keys):
            self.calls += 1
            time.sleep(0.05)
            return {k: {"name": f"n{k}"} for k in keys}

    from arroyo_tpu.expr import Col

    conn = SlowLookup()
    op = LookupJoin({
        "connector": conn,
        "key_exprs": [Col("id")],
        "right_names": [("name", "name")],
        "join_type": "left",
        "max_concurrency": 16,
    })
    ctx = two_input_ctx("lookup")
    col = FakeCollector()
    n_batches, per = 12, 4
    t0 = time.perf_counter()
    for b in range(n_batches):
        ids = [b * per + j for j in range(per)]
        op.process_batch(kb(ids, ids, [f"v{c}" for c in ids]), ctx, col)
    _lookup_drain(op, ctx, col)
    elapsed = time.perf_counter() - t0
    assert elapsed < 0.3, f"lookups serialized: {elapsed:.2f}s for 12x50ms"
    rows = rows_of(col)
    assert len(rows) == n_batches * per
    # strict input order and exact join results
    assert [r["id"] for r in rows] == list(range(n_batches * per))
    assert all(r["name"] == f"n{r['id']}" for r in rows)
    assert conn.calls == n_batches


def test_device_join_probe_matches_numpy():
    """Device sort/search join phase (ops/join_probe.py) must yield exactly
    the host _hash_join_indices pairs, including duplicate keys on both
    sides and sentinel-adjacent values."""
    import numpy as np
    from arroyo_tpu.operators.joins import _hash_join_indices
    from arroyo_tpu.ops.join_probe import device_join_start

    rng = np.random.default_rng(13)
    for n_l, n_r in ((5, 3), (100, 700), (1000, 1000), (0, 50), (50, 0)):
        lk = rng.integers(0, 40, size=n_l).astype(np.int64)
        rk = rng.integers(0, 40, size=n_r).astype(np.int64)
        if n_l > 4:
            lk[-1] = np.iinfo(np.int64).max  # collide with the pad sentinel
        want_li, want_ri = _hash_join_indices(lk, rk)
        got_li, got_ri = device_join_start(lk, rk).result()
        want = sorted(zip(want_li.tolist(), want_ri.tolist()))
        got = sorted(zip(got_li.tolist(), got_ri.tolist()))
        assert got == want, (n_l, n_r)


def test_instant_join_device_backend_end_to_end():
    """InstantJoin on the device backend (join-min-rows forced to 0 so every
    window takes the device path), with pipelined emission across several
    windows + watermarks, matches the numpy backend exactly."""
    from arroyo_tpu import config as cfg

    # force-device-join forces the device dispatch even though the test jax
    # platform IS the host cpu (where the adaptive gate prefers numpy)
    cfg.update({"device.join-min-rows": 0, "device.force-device-join": True})
    rng = np.random.default_rng(23)

    def run(backend):
        op = InstantJoin({
            "join_type": "full",
            "left_names": [("lid", "id"), ("lv", "v")],
            "right_names": [("rid", "id"), ("rv", "v")],
            "backend": backend,
        })
        ctx, col = two_input_ctx(), FakeCollector()
        for t in (100, 200, 300, 400):
            nl, nr = int(rng.integers(5, 60)), int(rng.integers(5, 60))
            lkeys = rng.integers(0, 12, size=nl).tolist()
            rkeys = rng.integers(0, 12, size=nr).tolist()
            op.process_batch(kb([t] * nl, lkeys, [f"l{t}_{i}" for i in range(nl)]),
                             ctx, col, input_index=0)
            op.process_batch(kb([t] * nr, rkeys, [f"r{t}_{i}" for i in range(nr)]),
                             ctx, col, input_index=1)
            op.handle_watermark(Watermark.event_time(t + 1), ctx, col)
        op.on_close(ctx, col)
        return sorted(
            repr((r["lid"], r["lv"], r["rid"], r["rv"], r[TIMESTAMP_FIELD]))
            for r in rows_of(col)
        )

    # same rng stream for both backends
    rng = np.random.default_rng(23)
    rows_np = run("numpy")
    rng = np.random.default_rng(23)
    rows_dev = run("jax")
    assert rows_dev == rows_np
    assert len(rows_dev) > 100
