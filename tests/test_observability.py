"""Metrics, admin server, logging, async UDFs.

Reference: arroyo-metrics (TaskCounters), arroyo-server-common (admin
server, init_logging), arroyo-worker/src/arrow/async_udf.rs.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.request

import numpy as np
import pytest

import arroyo_tpu
from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
from arroyo_tpu.metrics import RateTracker, registry
from arroyo_tpu.udf import drop_udf, register_udf


def _run_simple_pipeline(tmp_path, job_id):
    from arroyo_tpu.engine.engine import run_graph
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    arroyo_tpu._load_operators()
    src = tmp_path / "in.json"
    with open(src, "w") as f:
        for i in range(100):
            f.write(json.dumps({"x": i, "_timestamp": i}) + "\n")
    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    rows = []
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "single_file", "path": str(src), "schema": S}, 1))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "sink", EdgeType.FORWARD, S)
    run_graph(g, job_id=job_id, timeout=60)
    return rows


def test_task_counters_and_prometheus(tmp_path, _storage):
    registry.clear_job("metrics-job")
    rows = _run_simple_pipeline(tmp_path, "metrics-job")
    assert len(rows) == 100
    jm = registry.job_metrics("metrics-job")
    assert jm["src"]["arroyo_worker_messages_sent"] == 100
    assert jm["sink"]["arroyo_worker_messages_recv"] == 100
    assert jm["sink"]["arroyo_worker_bytes_recv"] > 0
    text = registry.prometheus_text()
    assert 'arroyo_worker_messages_sent{job="metrics-job",operator="src"' in text
    assert "# TYPE arroyo_worker_messages_recv counter" in text


def test_admin_server(tmp_path, _storage):
    from arroyo_tpu.server_common import AdminServer

    registry.clear_job("admin-job")
    _run_simple_pipeline(tmp_path, "admin-job")
    srv = AdminServer("worker", port=0).start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/status") as r:
            status = json.loads(r.read())
        assert status["healthy"] and status["service"] == "worker"
        with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/metrics") as r:
            text = r.read().decode()
        assert "arroyo_worker_batches_sent" in text
        with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/config") as r:
            conf = json.loads(r.read())
        assert "pipeline" in conf
    finally:
        srv.stop()


def test_init_logging_formats(capsys):
    from arroyo_tpu.server_common import init_logging

    for fmt in ("console", "json", "logfmt"):
        init_logging(fmt=fmt, level="INFO")
        logging.getLogger("arroyo.test").info("hello %s", fmt)
        err = capsys.readouterr().err
        assert "hello" in err
        if fmt == "json":
            assert json.loads(err.strip())["message"] == "hello json"
    # restore default handlers
    logging.getLogger().handlers.clear()


def test_rate_tracker():
    rt = RateTracker(window_s=10)
    rt.observe("k", 0, now=0.0)
    rt.observe("k", 500, now=5.0)
    assert rt.rate("k") == pytest.approx(100.0)
    assert rt.rate("missing") == 0.0


@pytest.mark.parametrize("ordered", [True, False])
def test_async_udf_sql(ordered, tmp_path, _storage):
    from arroyo_tpu.engine.engine import run_graph
    from arroyo_tpu.sql import plan_query

    arroyo_tpu._load_operators()
    name = f"audf_{'o' if ordered else 'u'}"

    @register_udf(name, return_dtype="int64", is_async=True,
                  max_concurrency=8, ordered=ordered)
    def _double(x):
        time.sleep(0.001)
        return int(x) * 2

    try:
        src = tmp_path / "in.json"
        with open(src, "w") as f:
            for i in range(60):
                f.write(json.dumps({"x": i, "_timestamp": i}) + "\n")
        sql = f"""
        CREATE TABLE t (x BIGINT) WITH (connector='single_file',
          path='{src}', format='json', type='source');
        SELECT x, {name}(x) AS dbl FROM t WHERE x % 3 = 0;
        """
        pp = plan_query(sql)
        ops = [n.op.value for n in pp.graph.topo_order()]
        assert "async_udf" in ops
        run_graph(pp.graph, job_id=f"audf-{ordered}", timeout=60)
        rows = sorted(pp.sinks[0].rows, key=lambda r: r["x"])
        assert [(r["x"], r["dbl"]) for r in rows] == [
            (i, i * 2) for i in range(0, 60, 3)
        ]
    finally:
        drop_udf(name)


def test_scalar_udf_sql(tmp_path, _storage):
    from arroyo_tpu.engine.engine import run_graph
    from arroyo_tpu.sql import plan_query

    arroyo_tpu._load_operators()

    @register_udf("triple", return_dtype="int64", vectorized=True)
    def _triple(x):
        return x * 3

    try:
        src = tmp_path / "in.json"
        with open(src, "w") as f:
            for i in range(10):
                f.write(json.dumps({"x": i, "_timestamp": i}) + "\n")
        sql = f"""
        CREATE TABLE t (x BIGINT) WITH (connector='single_file',
          path='{src}', format='json', type='source');
        SELECT triple(x) AS t3 FROM t;
        """
        pp = plan_query(sql)
        run_graph(pp.graph, job_id="sudf", timeout=60)
        assert sorted(r["t3"] for r in pp.sinks[0].rows) == [i * 3 for i in range(10)]
    finally:
        drop_udf("triple")


def test_admin_debug_endpoints(_storage):
    """Heap profile (tracemalloc) and thread dump on the admin server
    (reference: /debug/pprof/heap, arroyo-server-common/src/lib.rs:257)."""
    import json as _json
    import urllib.request

    from arroyo_tpu.server_common import AdminServer

    srv = AdminServer("test", port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        first = _json.load(urllib.request.urlopen(f"{base}/debug/pprof/heap", timeout=10))
        second = _json.load(urllib.request.urlopen(f"{base}/debug/pprof/heap", timeout=10))
        snap = second if "top" in second else first
        assert "top" in snap and isinstance(snap["top"], list) and snap["top"]
        stopped = _json.load(urllib.request.urlopen(
            f"{base}/debug/pprof/heap?stop", timeout=10))
        assert stopped["status"] == "tracing stopped"
        threads = _json.load(urllib.request.urlopen(f"{base}/debug/threads", timeout=10))
        assert any(k.startswith("MainThread-") for k in threads)
    finally:
        srv.stop()
        import tracemalloc

        tracemalloc.stop()
