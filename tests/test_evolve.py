"""Live pipeline evolution: versioned redeploy with proven state carry-over
and barrier-atomic blue/green cutover.

Three layers of proof:

- the "evolve mid-stream" axis of the smoke families (engine-level,
  deterministic): drain v1 behind a final checkpoint, prove the carry-over
  with the plan-diff pass, restore the evolved plan through the persisted
  mapping, and require the carried output prefix to stay BYTE-EXACT while
  the merged result still matches the goldens;
- the controller end-to-end path (evolve API -> Evolving -> drain ->
  plan-diff -> versioned redeploy -> cutover) with the full
  JOB_EVOLVE_STARTED/CLASSIFIED/CUTOVER/DONE lifecycle;
- the chaos axis: the drain trigger lost mid-evolution (watchdog
  re-triggers, never wedges) and a crash AT the cutover barrier (recovery
  converges on exactly one committed lineage).
"""

from __future__ import annotations

import glob
import json
import os
import time
import urllib.error
import urllib.request

import pytest

from test_plan_diff import add_noop_filter, add_projected_column, widen_window
from test_smoke import assert_outputs, build, canon, load_sql, read_output

SMOKE = os.path.join(os.path.dirname(__file__), "smoke")


def _snapshot_parts(out: str) -> dict[str, bytes]:
    """Byte snapshot of every committed part file of ``out``."""
    files = {}
    for p in sorted(glob.glob(out) + glob.glob(out + ".*")):
        with open(p, "rb") as f:
            files[p] = f.read()
    return files


def _assert_prefix_untouched(before: dict[str, bytes]) -> None:
    """The carried prefix is immutable: every output file committed by the
    v1 set must still START byte-for-byte with what v1 wrote (the
    single_file sink rewrites one cumulative file per shard, so a carried
    sink appends after the prefix and a rebuilt sink writes elsewhere —
    either way the v1 bytes must survive unchanged)."""
    for p, data in before.items():
        assert os.path.exists(p), f"carried output file {p} vanished"
        with open(p, "rb") as f:
            assert f.read().startswith(data), \
                f"carried output prefix in {p} was rewritten"


def _drain_v1(sql: str, job_id: str, parallelism: int = 2, epochs: int = 3):
    """Run v1 mid-stream (source gate) and drain it behind a final
    checkpoint at ``epochs`` — the evolve drain, engine-level."""
    from arroyo_tpu import config as cfg

    cfg.update({"testing.source-gate-epochs": epochs})
    try:
        eng = build(sql, parallelism, job_id)
        eng.start()
        for e in range(1, epochs):
            assert eng.checkpoint_and_wait(e, timeout=60), f"epoch {e} hung"
        assert eng.checkpoint_and_wait(epochs, timeout=60, then_stop=True), \
            "the drain checkpoint did not complete"
        eng.join(timeout=120)
    finally:
        cfg.update({"testing.source-gate-epochs": 0})


def _evolve_mapping(old_sql: str, new_sql: str, job_id: str, epoch: int,
                    storage: str):
    """The controller's _finish_evolve, distilled: diff the plans and
    persist the proven mapping next to the drain checkpoint."""
    from arroyo_tpu.analysis.plan_diff import diff_plans
    from arroyo_tpu.sql import plan_query
    from arroyo_tpu.state.tables import write_evolution_mapping

    diff = diff_plans(plan_query(old_sql).graph, plan_query(new_sql).graph)
    assert not diff.rejected, [d.to_dict() for d in diff.diagnostics]
    write_evolution_mapping(storage, job_id, epoch, diff.mapping)
    return diff


# ------------------------------------------------- evolve mid-stream axis


def test_evolve_axis_select_star_add_projected_column(tmp_path, _storage):
    """select_star evolves mid-stream to project an extra column: the sink
    is rebuilt (schema changed), the source's offsets carry, every v1 part
    file stays byte-exact, and the merged output — old-shape prefix plus
    new-shape suffix — still covers the golden multiset exactly once."""
    out = str(tmp_path / "out.json")
    out2 = str(tmp_path / "out2.json")
    sql = load_sql("select_star", out)
    evolved = add_projected_column(sql, out, out2)
    job_id = "select-star-evolve"

    _drain_v1(sql, job_id)
    prefix = _snapshot_parts(out)
    assert prefix, "the drain must leave a committed v1 prefix"

    diff = _evolve_mapping(sql, evolved, job_id, 3, _storage)
    actions = {c.node_id: c.action for c in diff.classifications}
    assert "rebuilt" in actions.values() and "carried" in actions.values()

    eng2 = build(evolved, 2, job_id, restore_epoch=3)
    eng2.run_to_completion(timeout=180)

    _assert_prefix_untouched(prefix)
    # the rebuilt sink wrote elsewhere: the v1 files are EXACTLY as committed
    assert _snapshot_parts(out) == prefix
    old_shape = read_output(out)
    new_shape = read_output(out2)
    assert old_shape, "no carried-prefix rows survived"
    assert new_shape, "the evolved plan never produced output"
    assert all("location2" not in r for r in old_shape)
    for r in new_shape:
        assert r["location2"] == r["location"]
    # exactly-once across the cutover: the carried source offsets make the
    # old-shape prefix plus the new-shape suffix cover the golden multiset
    # with no duplicated or lost row
    projected = old_shape + [{k: v for k, v in r.items() if k != "location2"}
                             for r in new_shape]
    with open(os.path.join(SMOKE, "golden", "select_star.json")) as f:
        golden = [json.loads(l) for l in f if l.strip()]
    assert sorted(canon(r) for r in projected) == \
        sorted(canon(r) for r in golden)


def test_evolve_axis_sliding_window_add_filter(tmp_path, _storage):
    """sliding_window evolves mid-stream to add a (semantically empty)
    filter: the hop-window aggregation state and the sink both carry, the
    v1 prefix stays byte-exact, and the final output is the unchanged
    golden — windows spanning the evolution point lose nothing."""
    out = str(tmp_path / "out.json")
    sql = load_sql("sliding_window", out)
    evolved = add_noop_filter(sql)
    job_id = "sliding-evolve"

    _drain_v1(sql, job_id)
    prefix = _snapshot_parts(out)
    assert prefix, "the drain must leave a committed v1 prefix"

    diff = _evolve_mapping(sql, evolved, job_id, 3, _storage)
    carried = [c.node_id for c in diff.classifications
               if c.action == "carried"]
    assert any("sliding_aggregate" in n for n in carried), carried

    eng2 = build(evolved, 2, job_id, restore_epoch=3)
    eng2.run_to_completion(timeout=180)

    _assert_prefix_untouched(prefix)
    assert_outputs("sliding_window", out)


def test_evolve_axis_tumbling_widen_window_rejected(tmp_path, _storage):
    """tumbling_aggregates must NOT evolve into a widened window: the
    plan-diff pass hard-rejects it (AR010) at plan time, and the restore
    path refuses the mismatched plan without a mapping — the drained
    lineage stays restorable under the ORIGINAL definition only."""
    from arroyo_tpu.analysis.plan_diff import diff_plans
    from arroyo_tpu.sql import plan_query

    out = str(tmp_path / "out.json")
    sql = load_sql("tumbling_aggregates", out)
    evolved = widen_window(sql)
    job_id = "tumbling-evolve-reject"

    _drain_v1(sql, job_id)

    diff = diff_plans(plan_query(sql).graph, plan_query(evolved).graph)
    assert diff.rejected
    assert any(d.rule_id == "AR010" and d.severity.name == "ERROR"
               for d in diff.diagnostics)

    # satellite: the plan fingerprint stamped into the drain checkpoint's
    # metadata makes a mapping-less restore of the changed plan fail
    # LOUDLY instead of misreading the window state
    eng_bad = build(evolved, 2, job_id, restore_epoch=3)
    with pytest.raises(RuntimeError, match="evolution mapping"):
        eng_bad.build()

    # the original plan still restores and finishes to the goldens
    eng2 = build(sql, 2, job_id, restore_epoch=3)
    eng2.run_to_completion(timeout=180)
    assert_outputs("tumbling_aggregates", out)


def test_restore_refuses_mapping_for_wrong_plan_pair(tmp_path, _storage):
    """A mapping proven for a different old->new plan pair must not be
    honored: the gate compares both hashes, not just presence."""
    from arroyo_tpu.state.tables import write_evolution_mapping

    out = str(tmp_path / "out.json")
    sql = load_sql("select_star", out)
    evolved = add_projected_column(sql, out)
    job_id = "select-star-bad-mapping"
    _drain_v1(sql, job_id)
    write_evolution_mapping(_storage, job_id, 3, {
        "old_plan_hash": "0" * 16, "new_plan_hash": "1" * 16,
        "nodes": {}, "dropped": []})
    eng = build(evolved, 2, job_id, restore_epoch=3)
    with pytest.raises(RuntimeError, match="different plan pair"):
        eng.build()


# -------------------------------------------- controller + API end to end


def _assert_select_star_covered(out: str, out2: str) -> None:
    """Merged v1 + v2 output covers the select_star golden exactly once
    (the evolved column projected away)."""
    rows = read_output(out) + [
        {k: v for k, v in r.items() if k != "location2"}
        for r in read_output(out2)]
    with open(os.path.join(SMOKE, "golden", "select_star.json")) as f:
        golden = [json.loads(l) for l in f if l.strip()]
    assert sorted(canon(r) for r in rows) == sorted(canon(r) for r in golden)


def _api_req(port, method, path, body=None):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r) as resp:
        return json.loads(resp.read())


def test_live_evolve_midstream_end_to_end(tmp_path, _storage):
    """POST /pipelines/<id>/evolve on a running job: the controller drains
    v1 behind a final checkpoint (Running -> Evolving), proves the
    carry-over, bumps the pipeline version, restores the evolved plan
    through the mapping, and releases withheld commits at the cutover
    barrier — full event lifecycle, golden-exact merged output."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.obs.events import trail

    out = str(tmp_path / "out.json")
    out2 = str(tmp_path / "out2.json")
    sql = load_sql("select_star", out)
    evolved = add_projected_column(sql, out, out2)
    db = Database()
    cfg.update({"testing.source-read-delay-micros": 5000,
                "checkpoint.interval-ms": 150})
    api = ApiServer(db, port=0).start()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("cars", sql, 1)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        time.sleep(0.2)  # let v1 commit some prefix
        resp = _api_req(api.port, "POST",
                        f"/api/v1/pipelines/{pid}/evolve",
                        {"query": evolved})
        assert resp["job_id"] == jid and resp["version"] == 2
        actions = {c["node_id"]: c["action"] for c in resp["classifications"]}
        assert "carried" in actions.values()
        # the job must pass through Evolving on its way back to Running
        seen = set()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            seen.add(db.get_job(jid)["state"])
            if "Evolving" in seen and db.get_job(jid)["state"] in (
                    "Running", "Finished"):
                break
            time.sleep(0.01)
        assert "Evolving" in seen, f"states seen: {seen}"
        cfg.update({"testing.source-read-delay-micros": 0})
        assert ctl.wait_for_state(jid, "Finished", timeout=120) == "Finished"

        # versioned redeploy persisted: the pipeline now IS the evolved SQL
        p = db.get_pipeline(pid)
        assert int(p["version"]) == 2 and p["query"] == evolved
        assert db.get_job(jid)["desired_query"] is None
        # the evolved set restored THROUGH the drain checkpoint
        assert ctl.jobs[jid].restore_epoch is not None

        t = trail(db.list_events(jid))
        for code in ("JOB_EVOLVE_STARTED", "JOB_EVOLVE_CLASSIFIED",
                     "JOB_EVOLVE_CUTOVER", "JOB_EVOLVE_DONE"):
            assert code in t, f"{code} missing from event trail: {t}"

        assert read_output(out2), "no evolved output"
        _assert_select_star_covered(out, out2)

        # a terminal job cannot evolve
        with pytest.raises(urllib.error.HTTPError) as ei:
            _api_req(api.port, "POST", f"/api/v1/pipelines/{pid}/evolve",
                     {"query": sql})
        assert ei.value.code == 409
    finally:
        cfg.update({"testing.source-read-delay-micros": 0,
                    "checkpoint.interval-ms": 10_000})
        ctl.stop()
        api.stop()


def test_evolve_api_rejects_incompatible_at_plan_time(tmp_path, _storage):
    """An incompatible evolution dies at the API with the AR-series
    diagnostic and classification detail; the job row is never touched —
    it must never reach Scheduling under the new plan."""
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import Database

    out = str(tmp_path / "out.json")
    sql = load_sql("tumbling_aggregates", out)
    db = Database()
    api = ApiServer(db, port=0).start()
    try:
        pid = db.create_pipeline("agg", sql, 1)
        jid = db.create_job(pid)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _api_req(api.port, "POST", f"/api/v1/pipelines/{pid}/evolve",
                     {"query": widen_window(sql)})
        assert ei.value.code == 400
        payload = json.loads(ei.value.read())
        assert "AR010" in payload["error"]
        assert any(c["action"] == "incompatible"
                   for c in payload["classifications"])
        assert any(d["rule"] == "AR010" for d in payload["diagnostics"])
        # never actuated: no desired_query, job state untouched
        job = db.get_job(jid)
        assert job["desired_query"] is None
        assert job["state"] == "Created"

        # noop: re-submitting the current query changes nothing
        resp = _api_req(api.port, "POST",
                        f"/api/v1/pipelines/{pid}/evolve", {"query": sql})
        assert resp.get("noop") is True
        assert db.get_job(jid)["desired_query"] is None

        # a broken evolved query is a 400, not a 500
        with pytest.raises(urllib.error.HTTPError) as ei:
            _api_req(api.port, "POST", f"/api/v1/pipelines/{pid}/evolve",
                     {"query": "SELECT FROM nothing"})
        assert ei.value.code == 400
    finally:
        api.stop()


def test_evolve_api_requires_live_job(tmp_path, _storage):
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import Database

    out = str(tmp_path / "out.json")
    sql = load_sql("select_star", out)
    db = Database()
    api = ApiServer(db, port=0).start()
    try:
        pid = db.create_pipeline("cars", sql, 1)
        # compatible evolution, but nothing running to evolve
        with pytest.raises(urllib.error.HTTPError) as ei:
            _api_req(api.port, "POST", f"/api/v1/pipelines/{pid}/evolve",
                     {"query": add_projected_column(sql, out)})
        assert ei.value.code == 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            _api_req(api.port, "POST", "/api/v1/pipelines/nope/evolve",
                     {"query": sql})
        assert ei.value.code == 404
    finally:
        api.stop()


# ------------------------------------------------------------- chaos axis


@pytest.mark.chaos
def test_chaos_evolve_drain_command_lost(tmp_path, _storage):
    """Chaos site `evolve_drain`: the final-checkpoint drain trigger of a
    live evolution is dropped. The stuck-epoch watchdog must re-trigger the
    drain (then_stop intact) and the evolution must still complete with
    golden-exact output — never a wedged Evolving job."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.obs.events import trail

    out = str(tmp_path / "out.json")
    out2 = str(tmp_path / "out2.json")
    sql = load_sql("select_star", out)
    evolved = add_projected_column(sql, out, out2)
    db = Database()
    inj = faults.install("evolve_drain:drop@step=1", seed=1337)
    cfg.update({"checkpoint.interval-ms": 10_000,  # no periodic epochs
                "checkpoint.timeout-ms": 400,
                "testing.source-read-delay-micros": 6000})
    api = ApiServer(db, port=0).start()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("cars", sql, 1)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        time.sleep(0.2)
        _api_req(api.port, "POST", f"/api/v1/pipelines/{pid}/evolve",
                 {"query": evolved})
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(c["state"] == "failed" for c in db.list_checkpoints(jid)):
                break
            time.sleep(0.02)
        assert any(c["state"] == "failed" for c in db.list_checkpoints(jid)), \
            "dropped drain trigger was never declared wedged"
        assert inj.fired_log, "evolve_drain drop never fired"
        cfg.update({"testing.source-read-delay-micros": 0})
        assert ctl.wait_for_state(jid, "Finished", timeout=120) == "Finished"
        assert int(db.get_pipeline(pid)["version"]) == 2
        t = trail(db.list_events(jid))
        assert "EPOCH_WEDGED" in t
        assert "JOB_EVOLVE_DONE" in t
        _assert_select_star_covered(out, out2)
    finally:
        faults.clear()
        cfg.update({"testing.source-read-delay-micros": 0,
                    "checkpoint.interval-ms": 10_000,
                    "checkpoint.timeout-ms": 600_000})
        ctl.stop()
        api.stop()


@pytest.mark.chaos
def test_chaos_crash_at_cutover_barrier_single_lineage(tmp_path, _storage):
    """Chaos site `evolve_cutover`: crash the evolved set AT the blue/green
    barrier — after its first epoch's metadata is durable, before any
    withheld commit is released. Recovery must converge on exactly one
    committed lineage: the restored set re-delivers the staged commits
    idempotently, the lifecycle completes, and the merged output is still
    golden-exact with no duplicated or lost row."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.obs.events import trail

    out = str(tmp_path / "out.json")
    out2 = str(tmp_path / "out2.json")
    sql = load_sql("select_star", out)
    evolved = add_projected_column(sql, out, out2)
    db = Database()
    inj = faults.install("evolve_cutover:crash@step=1", seed=1337)
    cfg.update({"checkpoint.interval-ms": 150,
                "testing.source-read-delay-micros": 5000})
    api = ApiServer(db, port=0).start()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("cars", sql, 1)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        time.sleep(0.2)
        _api_req(api.port, "POST", f"/api/v1/pipelines/{pid}/evolve",
                 {"query": evolved})
        # the evolved set's first durable epoch fires the injected crash;
        # the controller restores it and the evolution still completes
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline and not inj.fired_log:
            time.sleep(0.02)
        assert inj.fired_log, "cutover crash never fired"
        cfg.update({"testing.source-read-delay-micros": 0})
        assert ctl.wait_for_state(jid, "Finished", timeout=120) == "Finished"
        assert int(db.get_job(jid)["restarts"]) >= 1, \
            "the cutover crash never cost a restart"
        assert int(db.get_pipeline(pid)["version"]) == 2
        t = trail(db.list_events(jid))
        assert "JOB_EVOLVE_CUTOVER" in t and "JOB_EVOLVE_DONE" in t
        # exactly one committed lineage: the goldens hold across the crash
        _assert_select_star_covered(out, out2)
    finally:
        faults.clear()
        cfg.update({"testing.source-read-delay-micros": 0,
                    "checkpoint.interval-ms": 10_000})
        ctl.stop()
        api.stop()
