"""Tumbling window aggregate: end-to-end graphs, watermark-driven emission,
device vs numpy backends, checkpoint/restore of window state."""

import numpy as np
import pytest

from arroyo_tpu.batch import Schema, TIMESTAMP_FIELD
from arroyo_tpu.engine import Engine, run_graph
from arroyo_tpu.expr import BinOp, Col, Lit
from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

DUMMY = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])


def windowed_count_graph(rows, backend, count=1000, width_micros=1_000_000,
                         parallelism=1, agg_parallelism=1):
    """impulse (1ms event spacing) -> watermark -> key(counter%7) ->
    tumbling count+sum -> vec."""
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "impulse", "message_count": count,
        "interval_micros": 1000, "start_time_micros": 0}, parallelism))
    g.add_node(Node("wm", OpName.WATERMARK, {"expr": Col(TIMESTAMP_FIELD)}, parallelism))
    g.add_node(Node("key", OpName.KEY,
                    {"keys": [("k", BinOp("%", Col("counter"), Lit(7)))]}, parallelism))
    g.add_node(Node("agg", OpName.TUMBLING_AGGREGATE, {
        "width_micros": width_micros,
        "key_fields": ["k"],
        "aggregates": [("cnt", "count", None), ("total", "sum", Col("counter"))],
        "input_dtype_of": lambda e: np.dtype(np.int64),
        "backend": backend,
    }, agg_parallelism))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "wm", EdgeType.FORWARD, DUMMY)
    g.add_edge("wm", "key", EdgeType.FORWARD, DUMMY)
    g.add_edge("key", "agg", EdgeType.SHUFFLE, DUMMY)
    g.add_edge("agg", "sink", EdgeType.SHUFFLE, DUMMY)
    return g


def expected_counts(count=1000, width_micros=1_000_000, interval=1000):
    """counter c has ts=c*interval, key=c%7; window w covers
    [w*width, (w+1)*width)."""
    out = {}
    for c in range(count):
        ts = c * interval
        w = ts // width_micros
        k = c % 7
        cnt, tot = out.get((w, k), (0, 0))
        out[(w, k)] = (cnt + 1, tot + c)
    return out


def test_tumbling_array_agg_keyed():
    """Keyed array_agg: collect lists must survive the hash round-trip for
    keys whose 64-bit hash has the top bit set (signed-view store keys —
    r5 code-review regression) and match per-(window, key) membership."""
    rows: list = []
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "impulse", "message_count": 500,
        "interval_micros": 1000, "start_time_micros": 0}, 1))
    g.add_node(Node("wm", OpName.WATERMARK, {"expr": Col(TIMESTAMP_FIELD)}, 1))
    g.add_node(Node("key", OpName.KEY,
                    {"keys": [("k", BinOp("%", Col("counter"), Lit(13)))]}, 1))
    g.add_node(Node("agg", OpName.TUMBLING_AGGREGATE, {
        "width_micros": 100_000,
        "key_fields": ["k"],
        "aggregates": [("vals", "collect", Col("counter")),
                       ("cnt", "count", None)],
        "input_dtype_of": lambda e: np.dtype(np.int64),
        "backend": "numpy",
    }, 1))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "wm", EdgeType.FORWARD, DUMMY)
    g.add_edge("wm", "key", EdgeType.FORWARD, DUMMY)
    g.add_edge("key", "agg", EdgeType.SHUFFLE, DUMMY)
    g.add_edge("agg", "sink", EdgeType.FORWARD, DUMMY)
    run_graph(g, job_id="tw-array-agg", timeout=60)
    want = {}
    for c in range(500):
        want.setdefault((c * 1000 // 100_000, c % 13), []).append(c)
    got = {(r["window_start"] // 100_000, r["k"]): sorted(r["vals"]) for r in rows}
    assert got == {k: sorted(v) for k, v in want.items()}
    # collect lists align row-for-row with the numeric count lane
    assert all(len(r["vals"]) == r["cnt"] for r in rows)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_tumbling_count_sum(backend):
    rows: list = []
    g = windowed_count_graph(rows, backend)
    run_graph(g, job_id=f"tw-{backend}", timeout=60)
    got = {(r["window_start"] // 1_000_000, r["k"]): (r["cnt"], r["total"]) for r in rows}
    assert got == expected_counts()
    # window_end is start + width
    for r in rows:
        assert r["window_end"] - r["window_start"] == 1_000_000


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_tumbling_parallel_aggregation(backend):
    rows: list = []
    g = windowed_count_graph(rows, backend, count=2000, parallelism=2, agg_parallelism=2)
    run_graph(g, job_id=f"twp-{backend}", timeout=60)
    # two sources each emit counters 0..1999 -> doubled counts/sums
    exp = {k: (c * 2, t * 2) for k, (c, t) in expected_counts(2000).items()}
    got = {(r["window_start"] // 1_000_000, r["k"]): (r["cnt"], r["total"]) for r in rows}
    assert got == exp


def test_watermark_driven_incremental_emission():
    """Windows must close as the watermark passes them, not only at EOF."""
    from arroyo_tpu.config import update

    update({"pipeline.source-batch-size": 100})
    rows: list = []
    g = windowed_count_graph(rows, "numpy", count=5000, width_micros=200_000)
    eng = Engine(g, job_id="wm-incr")
    eng.start()
    eng.join(timeout=60)
    got = {(r["window_start"] // 200_000, r["k"]): (r["cnt"], r["total"]) for r in rows}
    assert got == expected_counts(5000, width_micros=200_000)


def test_late_data_dropped_not_reemitted():
    """Rows behind an already-emitted window are dropped, matching the
    reference's late-data policy (no duplicate window output)."""
    from arroyo_tpu.batch import Batch
    from arroyo_tpu.operators.base import OperatorContext
    from arroyo_tpu.state.tables import TableManager
    from arroyo_tpu.types import TaskInfo, Watermark
    from arroyo_tpu.windows.tumbling import TumblingAggregate

    class FakeCollector:
        def __init__(self):
            self.batches = []

        def collect(self, b):
            self.batches.append(b)

        def broadcast(self, s):
            pass

    op = TumblingAggregate({
        "width_micros": 1000,
        "key_fields": [],
        "aggregates": [("cnt", "count", None)],
        "backend": "numpy",
    })
    ti = TaskInfo("j", "agg", "tumbling_aggregate", 0, 1)
    ctx = OperatorContext(ti, None, TableManager(ti, "/tmp/unused"))
    col = FakeCollector()
    op.process_batch(Batch({"_timestamp": np.array([100, 900, 1500])}), ctx, col)
    op.handle_watermark(Watermark.event_time(1000), ctx, col)  # closes bin 0
    assert len(col.batches) == 1 and col.batches[0]["cnt"].tolist() == [2]
    # late row for the closed window must NOT re-open it
    op.process_batch(Batch({"_timestamp": np.array([200])}), ctx, col)
    op.handle_watermark(Watermark.event_time(2000), ctx, col)
    op.on_close(ctx, col)
    assert len(col.batches) == 2  # only bin 1 emitted afterwards
    assert col.batches[1]["cnt"].tolist() == [1]
    assert op.late_rows == 1


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_tumbling_checkpoint_restore(backend):
    """Checkpoint mid-stream with open windows, restore, finish: results must
    match an uninterrupted run (exactly-once window state)."""
    rows1: list = []
    count, width = 3000, 500_000
    g1 = windowed_count_graph(rows1, backend, count=count, width_micros=width)
    run_graph(g1, job_id=f"ref-{backend}", timeout=60)
    expected = {(r["window_start"], r["k"]): (r["cnt"], r["total"]) for r in rows1}

    rows2: list = []
    g2 = windowed_count_graph(rows2, backend, count=count, width_micros=width)
    # throttle so the checkpoint lands mid-stream
    g2.nodes["src"].config["event_rate"] = 2000
    eng = Engine(g2, job_id=f"ckptw-{backend}")
    eng.start()
    assert eng.checkpoint_and_wait(1, timeout=30)
    eng.stop()
    eng.join(timeout=30)
    emitted_at_stop = len(rows2)
    assert emitted_at_stop < len(rows1)

    rows3: list = []
    g3 = windowed_count_graph(rows3, backend, count=count, width_micros=width)
    eng3 = Engine(g3, job_id=f"ckptw-{backend}", restore_epoch=1)
    eng3.run_to_completion(timeout=60)
    # rows emitted BEFORE the checkpoint are part of the first run's output;
    # restored run re-emits only windows open at checkpoint time.
    merged = {}
    for r in rows2 + rows3:
        key = (r["window_start"], r["k"])
        # later (restored) results win for duplicated windows
        merged[key] = (r["cnt"], r["total"])
    assert merged == expected


def _fake_ctx(name="agg"):
    from arroyo_tpu.operators.base import OperatorContext
    from arroyo_tpu.state.tables import TableManager
    from arroyo_tpu.types import TaskInfo

    ti = TaskInfo("j", name, "tumbling_aggregate", 0, 1)
    return OperatorContext(ti, None, TableManager(ti, "/tmp/unused"))


class _Collector:
    def __init__(self):
        self.batches = []
        self.signals = []

    def collect(self, b):
        self.batches.append(b)

    def broadcast(self, s):
        self.signals.append(s)


def test_key_dict_horizon_is_monotone():
    """An out-of-order batch with a lower max bin must not lower a key's
    liveness horizon (advisor r2 high: a later eviction would delete values
    still resident on device)."""
    from arroyo_tpu.batch import Batch
    from arroyo_tpu.windows.tumbling import KeyDictionary

    kd = KeyDictionary(["name"])
    b1 = Batch({"name": np.array(["a"], dtype=object), "_timestamp": np.array([0])})
    kd.observe(np.array([7], dtype=np.uint64), np.array([5]), b1)
    # same key arrives again in an older (lower-bin) batch
    kd.observe(np.array([7], dtype=np.uint64), np.array([2]), b1)
    assert kd.last_bin[7] == 5
    kd.evict_closed(3)  # bins < 3 closed: key must survive (live through bin 5)
    assert 7 in kd.values
    cols = kd.lookup_columns(np.array([7], dtype=np.uint64))
    assert cols["name"].tolist() == ["a"]


def test_checkpoint_before_first_batch_keeps_key_lanes(tmp_path):
    """A barrier before any data must not freeze the aggregator before
    numeric key lanes are appended (advisor r2 medium: later updates would
    silently drop group-by key columns)."""
    from arroyo_tpu.batch import Batch
    from arroyo_tpu.operators.base import OperatorContext
    from arroyo_tpu.state.tables import TableManager
    from arroyo_tpu.types import CheckpointBarrier, TaskInfo, Watermark
    from arroyo_tpu.windows.tumbling import TumblingAggregate

    op = TumblingAggregate({
        "width_micros": 1000,
        "key_fields": ["k"],
        "aggregates": [("cnt", "count", None)],
        "backend": "numpy",
    })
    ti = TaskInfo("j", "agg", "tumbling_aggregate", 0, 1)
    ctx = OperatorContext(ti, None, TableManager(ti, str(tmp_path)))
    col = _Collector()
    op.handle_checkpoint(CheckpointBarrier(epoch=1, timestamp=0), ctx, col)
    assert op._agg is None  # not constructed by the empty checkpoint
    from arroyo_tpu.batch import KEY_FIELD

    b = Batch({
        "k": np.array([1, 2]),
        KEY_FIELD: np.array([1, 2], dtype=np.uint64),
        "_timestamp": np.array([100, 200]),
    })
    op.process_batch(b, ctx, col)
    op.handle_watermark(Watermark.event_time(2000), ctx, col)
    assert len(col.batches) == 1
    out = col.batches[0]
    assert sorted(out["k"].tolist()) == [1, 2]
    assert out["cnt"].tolist() == [1, 1]


def test_watermark_only_pending_is_bounded():
    """Watermark-only pending entries must respect the pipeline-depth bound
    during data gaps (advisor r2 medium: unbounded deque growth)."""
    from arroyo_tpu.types import Watermark
    from arroyo_tpu.windows.tumbling import TumblingAggregate, _PIPELINE_DEPTH

    class StuckHandle:
        def is_ready(self):
            return False

        def result(self):
            return (np.empty(0, dtype=np.uint64), np.empty(0, dtype=np.int32), [])

    op = TumblingAggregate({
        "width_micros": 1000,
        "key_fields": [],
        "aggregates": [("cnt", "count", None)],
        "backend": "jax",
    })
    ctx = _fake_ctx()
    col = _Collector()
    # simulate a dispatched close whose fetch never completes on its own
    op.base_bin = 0
    op._pending.append((StuckHandle(), 1, Watermark.event_time(1000), op._batch_seq))
    for i in range(2, 2 + 4 * _PIPELINE_DEPTH):
        op.handle_watermark(Watermark.event_time(i * 1000), ctx, col)
        assert len(op._pending) <= _PIPELINE_DEPTH
    # every held watermark was eventually broadcast (none lost)
    op.on_close(ctx, col)
    assert len(op._pending) == 0
