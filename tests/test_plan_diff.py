"""Plan-diff pass for live evolution (analysis/plan_diff.py).

Covers the classification taxonomy (carried / rebuilt / dropped /
incompatible / stateless), the AR010-012 diagnostics, the evolution mapping
the restore path consumes, and the plan fingerprint stamped into checkpoint
metadata (stable across replans and rescales, sensitive to anything that
changes the meaning of checkpointed bytes).
"""

from __future__ import annotations

import os

import pytest

from test_smoke import load_sql

SMOKE = os.path.join(os.path.dirname(__file__), "smoke")


def _graph(sql: str):
    from arroyo_tpu.sql import plan_query

    return plan_query(sql).graph


def _load(name: str, out: str) -> str:
    return load_sql(name, out)


# evolved-query surgery shared with tests/test_evolve.py: each anchors on a
# unique fragment of the smoke query so the fixture files stay the oracle
def add_projected_column(sql: str, out: str, new_out: str = None) -> str:
    """select_star with an extra projected column: the sink schema changes,
    so the redefined sink also writes to a NEW path (``new_out``) — the v1
    prefix stays where the v1 sink committed it, immutable."""
    assert "SELECT * FROM cars" in sql
    return sql.replace(
        f"location TEXT\n) WITH (\n  connector = 'single_file',\n"
        f"  path = '{out}'",
        f"location TEXT,\n  location2 TEXT\n) WITH (\n"
        f"  connector = 'single_file',\n  path = '{new_out or out}'",
    ).replace(
        "SELECT * FROM cars",
        "SELECT timestamp, driver_id, event_type, location, "
        "location AS location2 FROM cars",
    )


def add_noop_filter(sql: str) -> str:
    """sliding_window with a semantically-empty filter (prices are >= 0)."""
    assert "FROM bids\n" in sql
    return sql.replace("FROM bids\n", "FROM bids WHERE price >= 0\n")


def widen_window(sql: str) -> str:
    """tumbling_aggregates with the window widened 10s -> 20s."""
    assert "interval '10 seconds'" in sql
    return sql.replace("interval '10 seconds'", "interval '20 seconds'")


def _by_action(diff):
    out: dict[str, list] = {}
    for c in diff.classifications:
        out.setdefault(c.action, []).append(c)
    return out


def test_fingerprint_stable_roundtrip_and_rescale_invariant(tmp_path):
    from arroyo_tpu.graph import Graph
    from arroyo_tpu.analysis.plan_diff import plan_fingerprint
    from arroyo_tpu.sql.planner import set_parallelism

    sql = _load("select_star", str(tmp_path / "o.json"))
    g1, g2 = _graph(sql), _graph(sql)
    fp = plan_fingerprint(g1)
    assert fp and plan_fingerprint(g2) == fp, "replanning must not move the fp"
    # parallelism is deliberately excluded: a rescale restores the same fp
    set_parallelism(g2, 3)
    assert plan_fingerprint(g2) == fp
    # the control plane ships IR through dumps/loads; the fp must survive
    assert plan_fingerprint(Graph.loads(g1.dumps())) == fp
    # a different pipeline is a different fp
    other = _graph(_load("tumbling_aggregates", str(tmp_path / "o2.json")))
    assert plan_fingerprint(other) != fp


def test_identical_plans_carry_everything(tmp_path):
    from arroyo_tpu.analysis.plan_diff import diff_plans, node_identity

    sql = _load("tumbling_aggregates", str(tmp_path / "o.json"))
    old, new = _graph(sql), _graph(sql)
    diff = diff_plans(old, new)
    assert not diff.rejected and not diff.diagnostics
    by = _by_action(diff)
    stateful = [n.node_id for n in new.topo_order() if node_identity(n).stateful]
    assert sorted(c.node_id for c in by.get("carried", [])) == sorted(stateful)
    assert not by.get("incompatible") and not by.get("dropped")
    assert diff.mapping["old_plan_hash"] == diff.mapping["new_plan_hash"]
    for nid in stateful:
        assert diff.mapping["nodes"][nid]["action"] == "carried"


def test_add_projected_column_sink_rebuilt_rest_carried(tmp_path):
    from arroyo_tpu.analysis.plan_diff import diff_plans

    out = str(tmp_path / "o.json")
    sql = _load("select_star", out)
    diff = diff_plans(_graph(sql), _graph(add_projected_column(sql, out)))
    assert not diff.rejected
    by = _by_action(diff)
    # the redefined sink restarts empty (its buffers flush at the drain
    # barrier); the source's offsets carry so no row is lost or replayed
    rebuilt = by.get("rebuilt", [])
    assert len(rebuilt) == 1 and rebuilt[0].node_id.startswith("sink")
    assert rebuilt[0].from_node and rebuilt[0].from_node.startswith("sink")
    assert any(c.node_id.startswith("source") for c in by.get("carried", []))
    assert {d.rule_id for d in diff.diagnostics} == {"AR011"}
    assert all(d.severity.name == "INFO" for d in diff.diagnostics)
    # the old sink's buffered state is explicitly dropped in the mapping so
    # the engine's stale-operator check knows it was accounted for
    assert rebuilt[0].from_node in diff.mapping["dropped"]


def test_add_filter_windows_carried(tmp_path):
    from arroyo_tpu.analysis.plan_diff import diff_plans

    sql = _load("sliding_window", str(tmp_path / "o.json"))
    diff = diff_plans(_graph(sql), _graph(add_noop_filter(sql)))
    assert not diff.rejected, [d.to_dict() for d in diff.diagnostics]
    by = _by_action(diff)
    assert any("sliding_aggregate" in c.node_id
               for c in by.get("carried", [])), by
    assert not by.get("incompatible")


def test_widen_window_rejected_ar010(tmp_path):
    from arroyo_tpu.analysis.plan_diff import diff_plans

    sql = _load("tumbling_aggregates", str(tmp_path / "o.json"))
    diff = diff_plans(_graph(sql), _graph(widen_window(sql)))
    assert diff.rejected
    errs = [d for d in diff.diagnostics if d.severity.name == "ERROR"]
    assert errs and all(d.rule_id == "AR010" for d in errs)
    by = _by_action(diff)
    assert by.get("incompatible"), "the widened window must be named"
    assert all(c.from_node for c in by["incompatible"])


def test_removed_aggregation_dropped_ar012(tmp_path):
    from arroyo_tpu.analysis.plan_diff import diff_plans

    out = str(tmp_path / "o.json")
    old_sql = _load("tumbling_aggregates", out)
    # the evolved plan removes the aggregation entirely: passthrough of the
    # same source into a sink of the raw schema
    new_sql = f"""
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '{os.path.join(SMOKE, "inputs")}/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE raw_output (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '{out}',
  format = 'json',
  type = 'sink'
);
INSERT INTO raw_output SELECT * FROM impulse_source;
"""
    diff = diff_plans(_graph(old_sql), _graph(new_sql))
    # dropping state is allowed — loudly (WARNING), never silently
    assert not diff.rejected
    warns = [d for d in diff.diagnostics if d.rule_id == "AR012"]
    assert warns and all(d.severity.name == "WARNING" for d in warns)
    by = _by_action(diff)
    assert by.get("dropped")
    for c in by["dropped"]:
        assert c.node_id in diff.mapping["dropped"]


def test_mapping_shape_matches_restore_contract(tmp_path):
    """The sidecar the controller persists is exactly what Engine.build /
    TableManager.restore consume: node actions keyed by NEW id, carried
    entries naming their source node and tables, hashes for the gate."""
    from arroyo_tpu.analysis.plan_diff import diff_plans, plan_fingerprint

    out = str(tmp_path / "o.json")
    sql = _load("sliding_window", out)
    old, new = _graph(sql), _graph(add_noop_filter(sql))
    diff = diff_plans(old, new)
    m = diff.mapping
    assert m["old_plan_hash"] == plan_fingerprint(old)
    assert m["new_plan_hash"] == plan_fingerprint(new)
    assert m["old_plan_hash"] != m["new_plan_hash"]
    for nid, entry in m["nodes"].items():
        assert nid in new.nodes
        assert entry["action"] in ("carried", "rebuilt", "stateless")
        if entry["action"] == "carried":
            assert entry["from"] in old.nodes
            assert isinstance(entry["tables"], list)


def test_evolution_mapping_sidecar_roundtrip(tmp_path, _storage):
    from arroyo_tpu.state.tables import (read_evolution_mapping,
                                         write_evolution_mapping)

    mapping = {"old_plan_hash": "a" * 16, "new_plan_hash": "b" * 16,
               "nodes": {"window_1_w": {"action": "carried",
                                        "from": "window_0_w",
                                        "tables": ["w"]}},
               "dropped": ["sink_2_old"]}
    assert read_evolution_mapping(_storage, "job-x", 3) is None
    write_evolution_mapping(_storage, "job-x", 3, mapping)
    assert read_evolution_mapping(_storage, "job-x", 3) == mapping
    # epoch-keyed: a different epoch sees nothing
    assert read_evolution_mapping(_storage, "job-x", 4) is None
