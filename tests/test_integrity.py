"""Checkpoint integrity & disaster recovery (state/integrity.py): the
checksummed-envelope write side, the quarantine-and-fall-back restore
ladder, the offline fsck walker, the `corrupt` chaos action, and the
unified bad_data drop policy — one test per corruption class (truncated
table file, bit-flipped sidecar, missing spill run, torn marker) asserting
detection, quarantine, and byte-exact fallback."""

import json
import os

import pytest

from arroyo_tpu import faults
from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
from arroyo_tpu.engine import Engine
from arroyo_tpu.graph import EdgeType, Graph, Node, OpName
from arroyo_tpu.state import storage
from arroyo_tpu.state.integrity import (
    fold_integrity,
    fsck_job,
    latest_valid_checkpoint,
    verify_epoch,
)
from arroyo_tpu.state.tables import (
    QUARANTINE_MARKER,
    QUARANTINED_METADATA,
    RestoreError,
    TableManager,
    checkpoint_dir,
    cleanup_checkpoints,
    dump_json_with_integrity,
    is_quarantined,
    latest_complete_checkpoint,
    quarantine_epoch,
    read_job_checkpoint_metadata,
    write_job_checkpoint_metadata,
)
from arroyo_tpu.operators.base import TableSpec
from arroyo_tpu.types import TaskInfo

DUMMY = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])


def _build(rows, count=5000):
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE,
                    {"connector": "impulse", "message_count": count,
                     "event_rate": 5000}, 1))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "sink", EdgeType.FORWARD, DUMMY)
    return g


def _run_epochs(job_id, n_epochs=2):
    """Run an impulse->vec pipeline, checkpoint ``n_epochs`` times mid-
    stream, then stop without finishing (state survives on disk)."""
    rows: list = []
    eng = Engine(_build(rows), job_id=job_id)
    eng.start()
    for e in range(1, n_epochs + 1):
        assert eng.checkpoint_and_wait(e, timeout=30)
    eng.stop()
    eng.join(timeout=30)
    return rows


def _table_files(storage_url, job_id, epoch):
    """Every (path, name) table file under one epoch's operator dirs."""
    out = []
    d = checkpoint_dir(storage_url, job_id, epoch)
    for opd in sorted(os.listdir(d)):
        p = os.path.join(d, opd)
        if not (opd.startswith("operator-") and os.path.isdir(p)):
            continue
        for fn in sorted(os.listdir(p)):
            if fn.startswith("table-"):
                out.append((os.path.join(p, fn), f"{opd}/{fn}"))
    return out


def _sidecars(storage_url, job_id, epoch):
    out = []
    d = checkpoint_dir(storage_url, job_id, epoch)
    for opd in sorted(os.listdir(d)):
        p = os.path.join(d, opd)
        if not (opd.startswith("operator-") and os.path.isdir(p)):
            continue
        for fn in sorted(os.listdir(p)):
            if fn.startswith("metadata-") and fn.endswith(".json"):
                out.append(os.path.join(p, fn))
    return out


def _bitflip(path):
    with open(path, "rb") as f:
        data = f.read()
    mid = len(data) // 2
    with open(path, "wb") as f:
        f.write(data[:mid] + bytes([data[mid] ^ 0x01]) + data[mid + 1:])


def _errors(diags):
    from arroyo_tpu.analysis import Severity

    return [d for d in diags if d.severity == Severity.ERROR]


# ---------------------------------------------------------------- write side


def test_marker_carries_integrity_manifest(_storage):
    _run_epochs("intg-manifest", n_epochs=1)
    marker = read_job_checkpoint_metadata(_storage, "intg-manifest", 1)
    manifest = marker.get("integrity")
    assert manifest, "job-level marker must fold the per-epoch manifest"
    for rel, env in manifest.items():
        assert rel.startswith("operator-")
        assert set(env) >= {"crc", "len", "algo"}
    # every manifest entry names a real artifact whose bytes verify
    cdir = checkpoint_dir(_storage, "intg-manifest", 1)
    for rel, env in manifest.items():
        data = storage.read_bytes(os.path.join(cdir, rel))
        storage.verify_envelope(data, env, rel)


def test_fold_integrity_shapes():
    metas = [{"node_id": "src",
              "files": [{"file": "table-s-000.bin", "table": "s",
                         "crc": 7, "len": 3, "algo": "crc32"},
                        {"file": "legacy.bin", "table": "l"}],  # no envelope
              "sidecar": {"file": "metadata-000.json", "crc": 9, "len": 2,
                          "algo": "crc32"}},
             {"no_node": True}, None]
    m = fold_integrity(x for x in metas if x)
    assert m == {
        "operator-src/table-s-000.bin": {"crc": 7, "len": 3, "algo": "crc32"},
        "operator-src/metadata-000.json": {"crc": 9, "len": 2,
                                           "algo": "crc32"}}


def test_healthy_job_fsck_clean_and_cli_exit_zero(_storage, capsys):
    _run_epochs("intg-clean", n_epochs=2)
    diags = fsck_job(_storage, "intg-clean")
    assert not _errors(diags), [d.render() for d in diags]

    from arroyo_tpu.cli import main

    rc = main(["fsck", "intg-clean", "--storage-url", _storage])
    assert rc == 0
    assert "fsck" in capsys.readouterr().out


# ------------------------------------------------- corruption class: table


def test_truncated_table_file_quarantines_and_falls_back(_storage):
    _run_epochs("intg-trunc", n_epochs=2)
    path, rel = _table_files(_storage, "intg-trunc", 2)[0]
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:
        f.write(data[: len(data) // 2])

    # fsck detects the corruption as an FS005 ERROR before any restore runs
    diags = fsck_job(_storage, "intg-trunc")
    assert any(d.rule_id == "FS005" for d in _errors(diags))

    # the ladder quarantines epoch 2 and falls back to epoch 1
    quarantined = []
    epoch, skipped = latest_valid_checkpoint(
        _storage, "intg-trunc",
        on_quarantine=lambda e, r: quarantined.append((e, r)))
    assert epoch == 1
    assert [s["epoch"] for s in skipped] == [2]
    assert quarantined and quarantined[0][0] == 2
    assert is_quarantined(_storage, "intg-trunc", 2)
    d2 = checkpoint_dir(_storage, "intg-trunc", 2)
    # the marker was preserved, never deleted
    assert os.path.exists(os.path.join(d2, QUARANTINED_METADATA))
    assert os.path.exists(os.path.join(d2, QUARANTINE_MARKER))
    assert not os.path.exists(os.path.join(d2, "metadata.json"))
    # after quarantine the epoch downgrades to an FS003 warning, not ERROR
    diags2 = fsck_job(_storage, "intg-trunc")
    assert not _errors(diags2)
    assert any(d.rule_id == "FS003" for d in diags2)

    # restoring from the fallback epoch replays the gap byte-exactly
    rows: list = []
    eng = Engine(_build(rows), job_id="intg-trunc", restore_epoch=epoch)
    eng.run_to_completion(timeout=60)
    counters = sorted(r["counter"] for r in rows)
    assert counters == list(range(counters[0], 5000))


# ----------------------------------------------- corruption class: sidecar


def test_bitflipped_sidecar_quarantines_and_falls_back(_storage):
    _run_epochs("intg-flip", n_epochs=2)
    _bitflip(_sidecars(_storage, "intg-flip", 2)[0])

    diags = fsck_job(_storage, "intg-flip")
    assert any(d.rule_id == "FS004" for d in _errors(diags))

    epoch, skipped = latest_valid_checkpoint(_storage, "intg-flip")
    assert epoch == 1
    assert [s["epoch"] for s in skipped] == [2]
    assert is_quarantined(_storage, "intg-flip", 2)


# --------------------------------------------- corruption class: spill run


def test_missing_spill_run_detected(_storage):
    """A sidecar referencing a spill run that is gone must fail the epoch
    (synthesized layout: spill runs outlive epochs, so liveness is part of
    epoch validity)."""
    job = "intg-spill"
    opdir = os.path.join(checkpoint_dir(_storage, job, 1), "operator-agg")
    storage.makedirs(opdir)
    table_bytes = b"columnar-bytes"
    env = storage.write_bytes(os.path.join(opdir, "table-t-000.bin"),
                              table_bytes)
    run = "run-aa-s0-e1-0.parquet"
    sidecar = {"node_id": "agg", "subtask_index": 0,
               "files": [{"file": "table-t-000.bin", "table": "t", **env,
                          "spill_runs": [run]}]}
    storage.write_text(os.path.join(opdir, "metadata-000.json"),
                       dump_json_with_integrity(sidecar))
    write_job_checkpoint_metadata(
        _storage, job, 1,
        {"operators": ["agg"], "integrity": fold_integrity([{
            "node_id": "agg", "files": sidecar["files"]}])})

    problems = verify_epoch(_storage, job, 1)
    assert any("spill run" in p for p in problems)
    diags = fsck_job(_storage, job)
    assert any(d.rule_id == "FS006" for d in _errors(diags))
    epoch, skipped = latest_valid_checkpoint(_storage, job)
    assert epoch is None and [s["epoch"] for s in skipped] == [1]

    # restore the run (footer-wrapped, as the spill writer produces) on a
    # fresh copy of the job: the epoch verifies again
    job2 = "intg-spill-ok"
    opdir2 = os.path.join(checkpoint_dir(_storage, job2, 1), "operator-agg")
    storage.makedirs(opdir2)
    storage.write_bytes(os.path.join(opdir2, "table-t-000.bin"), table_bytes)
    storage.write_text(os.path.join(opdir2, "metadata-000.json"),
                       dump_json_with_integrity(sidecar))
    write_job_checkpoint_metadata(_storage, job2, 1, {"operators": ["agg"]})
    rd = os.path.join(_storage, job2, "spill", "operator-agg")
    storage.makedirs(rd)
    with open(os.path.join(rd, run), "wb") as f:
        f.write(storage.wrap_footer(b"parquet-bytes"))
    assert verify_epoch(_storage, job2, 1) == []
    assert not _errors(fsck_job(_storage, job2))


def test_corrupt_spill_footer_is_fsck_error(_storage):
    job = "intg-footer"
    storage.makedirs(os.path.join(checkpoint_dir(_storage, job, 1)))
    write_job_checkpoint_metadata(_storage, job, 1, {"operators": []})
    rd = os.path.join(_storage, job, "spill", "operator-agg")
    storage.makedirs(rd)
    p = os.path.join(rd, "run-bb-s0-e1-0.parquet")
    with open(p, "wb") as f:
        f.write(storage.wrap_footer(b"payload-bytes"))
    _bitflip(p)
    diags = fsck_job(_storage, job)
    assert any(d.rule_id == "FS006" for d in _errors(diags))


# ------------------------------------------------ corruption class: marker


def test_torn_marker_unified_predicate_and_fallback(_storage):
    _run_epochs("intg-torn", n_epochs=2)
    marker = os.path.join(checkpoint_dir(_storage, "intg-torn", 2),
                          "metadata.json")
    with open(marker, "w") as f:
        f.write('{"job_id": "intg-torn", "epo')  # torn mid-write

    # selection and restore share ONE torn-marker predicate: both treat
    # the epoch as absent, never "complete for selection, torn for restore"
    assert read_job_checkpoint_metadata(_storage, "intg-torn", 2) is None
    assert latest_complete_checkpoint(_storage, "intg-torn") == 1

    diags = fsck_job(_storage, "intg-torn")
    assert any(d.rule_id == "FS002" for d in _errors(diags))

    epoch, skipped = latest_valid_checkpoint(_storage, "intg-torn")
    assert epoch == 1
    assert [s["epoch"] for s in skipped] == [2]
    assert is_quarantined(_storage, "intg-torn", 2)


def test_markerless_epoch_is_invisible_not_quarantined(_storage):
    """A directory with NO marker at all is a torn checkpoint the watchdog
    subsumes — the ladder skips it silently rather than quarantining."""
    _run_epochs("intg-nomark", n_epochs=2)
    os.remove(os.path.join(checkpoint_dir(_storage, "intg-nomark", 2),
                           "metadata.json"))
    epoch, skipped = latest_valid_checkpoint(_storage, "intg-nomark")
    assert epoch == 1 and skipped == []
    assert not is_quarantined(_storage, "intg-nomark", 2)
    diags = fsck_job(_storage, "intg-nomark")
    assert not _errors(diags)
    assert any(d.rule_id == "FS001" for d in diags)


# ------------------------------------------------------------- GC refusal


def test_gc_never_collects_a_quarantined_epoch(_storage):
    _run_epochs("intg-gc", n_epochs=2)
    quarantine_epoch(_storage, "intg-gc", 1, "test corruption evidence")
    removed = cleanup_checkpoints(_storage, "intg-gc", min_epoch=99)
    assert removed >= 1  # epoch 2 was collectable
    assert os.path.isdir(checkpoint_dir(_storage, "intg-gc", 1))
    assert not os.path.isdir(checkpoint_dir(_storage, "intg-gc", 2))
    assert is_quarantined(_storage, "intg-gc", 1)


# ------------------------------------------------------ corrupt chaos action


def test_corrupt_fault_action_write_side(_storage):
    """storage.put:corrupt=bitflip persists corrupted bytes while the
    envelope records the intended ones — exactly what the manifest is for:
    fsck flags it and the ladder refuses the epoch."""
    faults.install("storage.put:corrupt=bitflip@match=table-")
    _run_epochs("intg-chaos", n_epochs=1)
    faults.clear()
    diags = fsck_job(_storage, "intg-chaos")
    assert any(d.rule_id == "FS005" for d in _errors(diags))
    epoch, skipped = latest_valid_checkpoint(_storage, "intg-chaos")
    assert epoch is None
    assert [s["epoch"] for s in skipped] == [1]
    assert is_quarantined(_storage, "intg-chaos", 1)


def test_corrupt_fault_action_parses_and_rejects_bad_mode():
    from arroyo_tpu.faults.plan import PlanSyntaxError, parse_plan

    specs = parse_plan("storage.put:corrupt=truncate@match=sidecar")
    assert specs[0].action == "corrupt" and specs[0].arg == "truncate"
    with pytest.raises(PlanSyntaxError):
        parse_plan("storage.put:corrupt=zero")
    with pytest.raises(PlanSyntaxError):
        parse_plan("storage.put:corrupt")


# ------------------------------------------------------------ restore errors


def test_restore_error_carries_context(_storage):
    ti = TaskInfo("intg-re", "src", "source", 0, 1)
    tm = TableManager(ti, _storage)
    tm.global_keyed("s").insert(0, 42)
    tm.checkpoint(1, None)
    write_job_checkpoint_metadata(_storage, "intg-re", 1,
                                  {"operators": ["src"]})
    path, _rel = _table_files(_storage, "intg-re", 1)[0]
    _bitflip(path)
    tm2 = TableManager(ti, _storage)
    with pytest.raises(RestoreError) as ei:
        tm2.restore(1, [TableSpec("s", "global_keyed")])
    assert ei.value.epoch == 1
    assert ei.value.operator == "src"
    assert ei.value.path
    assert ei.value.cause is not None


def test_verify_off_skips_checksum_on_restore(_storage):
    """state.integrity.verify = off: a bit-flipped artifact sails through
    the ladder (operator chose to trust storage); fsck still catches it."""
    from arroyo_tpu import config as cfg

    _run_epochs("intg-off", n_epochs=1)
    path, _rel = _table_files(_storage, "intg-off", 1)[0]
    _bitflip(path)
    cfg.update({"state.integrity.verify": "off"})
    try:
        epoch, skipped = latest_valid_checkpoint(_storage, "intg-off")
        assert epoch == 1 and skipped == []
    finally:
        cfg.update({"state.integrity.verify": "restore"})
    assert any(d.rule_id == "FS005" for d in _errors(fsck_job(
        _storage, "intg-off")))


# ------------------------------------------------------------------- fsck IO


def test_fsck_cli_json_round_trip(_storage, capsys):
    _run_epochs("intg-json", n_epochs=1)
    path, _rel = _table_files(_storage, "intg-json", 1)[0]
    _bitflip(path)

    from arroyo_tpu.cli import main

    rc = main(["fsck", "intg-json", "--storage-url", _storage, "--json"])
    out = capsys.readouterr().out
    assert rc == 1  # ERROR findings exit 1, matching `lint`
    payload = json.loads(out)
    assert isinstance(payload, list) and payload
    for d in payload:
        assert set(d) == {"rule", "severity", "site", "message", "hint"}
    assert any(d["rule"] == "FS005" and d["severity"] == "error"
               for d in payload)


def test_fsck_api_endpoint(_storage):
    from arroyo_tpu.api.server import ApiServer
    from arroyo_tpu.controller import Database

    _run_epochs("intg-api", n_epochs=1)
    srv = ApiServer(Database(":memory:"), port=0)
    srv.start()
    try:
        import urllib.request
        from urllib.parse import quote

        url = (f"http://127.0.0.1:{srv.port}/api/v1/jobs/intg-api/fsck"
               f"?storage_url={quote(_storage, safe='')}")
        with urllib.request.urlopen(url, timeout=10) as resp:
            body = json.loads(resp.read())
        assert body["job_id"] == "intg-api"
        assert body["clean"] is True

        path, _rel = _table_files(_storage, "intg-api", 1)[0]
        _bitflip(path)
        with urllib.request.urlopen(url, timeout=10) as resp:
            body = json.loads(resp.read())
        assert body["clean"] is False
        assert any(d["rule"] == "FS005" for d in body["diagnostics"])
    finally:
        srv.stop()


# -------------------------------------------------------- unified bad_data


def test_bad_data_drop_counts_metric_and_event(_storage):
    from arroyo_tpu.formats.registry import make_deserializer
    from arroyo_tpu.metrics import registry
    from arroyo_tpu.obs.events import recorder

    schema = Schema.of([("v", "int64"), (TIMESTAMP_FIELD, "int64")])
    ti = TaskInfo("intg-bad", "src", "source", 0, 1)
    registry.clear_job("intg-bad")
    de = make_deserializer({"format": "json", "bad_data": "drop"},
                           schema, task_info=ti)
    de.deserialize(b"{not json")
    de.deserialize(b"{still not json")
    assert de.errors == 2
    assert registry.bad_records("intg-bad") == {"src": 2}
    line = f'arroyo_bad_records_total{{job="intg-bad",operator="src"}} 2'
    assert line in registry.prometheus_text()
    evs = [e for e in recorder.events("intg-bad")
           if e["code"] == "BAD_DATA_DROPPED"]
    # throttled: the first drop emits, the second rides the 30s window
    assert len(evs) == 1
    assert evs[0]["data"]["dropped"] == 1
    registry.clear_job("intg-bad")
    assert registry.bad_records("intg-bad") == {}


def test_bad_data_fail_still_raises(_storage):
    from arroyo_tpu.formats.registry import make_deserializer

    schema = Schema.of([("v", "int64"), (TIMESTAMP_FIELD, "int64")])
    de = make_deserializer({"format": "json"}, schema,
                           task_info=TaskInfo("intg-bad2", "src", "source",
                                              0, 1))
    with pytest.raises(Exception):
        de.deserialize(b"{nope")
    assert de.drop_bad_data(RuntimeError("transport")) is False


def test_transport_errors_share_the_drop_policy(_storage):
    """drop_bad_data is the transport-layer entry (http_conn routes its
    request failures through it): counted exactly like decode errors."""
    from arroyo_tpu.formats.registry import make_deserializer
    from arroyo_tpu.metrics import registry

    schema = Schema.of([("v", "int64"), (TIMESTAMP_FIELD, "int64")])
    ti = TaskInfo("intg-bad3", "src", "source", 0, 1)
    registry.clear_job("intg-bad3")
    de = make_deserializer({"format": "json", "bad_data": "drop"},
                           schema, task_info=ti)
    assert de.drop_bad_data(ConnectionError("reset")) is True
    assert registry.bad_records("intg-bad3") == {"src": 1}
    registry.clear_job("intg-bad3")
