"""Updating aggregate: retract/append emission, no-op suppression, TTL
eviction, updating input retractions, checkpoint/restore."""

import numpy as np
import pytest

from arroyo_tpu.batch import Batch, TIMESTAMP_FIELD
from arroyo_tpu.expr import Col
from arroyo_tpu.hashing import hash_columns
from arroyo_tpu.operators.base import OperatorContext
from arroyo_tpu.operators.updating_aggregate import (
    IS_RETRACT_FIELD,
    UpdatingAggregate,
    merge_updating_rows,
)
from arroyo_tpu.state.tables import TableManager
from arroyo_tpu.types import TaskInfo, Watermark


class FakeCollector:
    def __init__(self):
        self.batches = []

    def collect(self, b):
        self.batches.append(b)

    def broadcast(self, s):
        pass


def rows_of(col):
    out = []
    for b in col.batches:
        out.extend(b.to_pylist())
    return out


def make_op(aggs=None, ttl=None, storage="/tmp/upd-agg-unused"):
    cfg = {
        "key_fields": ["u"],
        "aggregates": aggs or [("cnt", "count", None), ("total", "sum", Col("v"))],
        "input_dtype_of": lambda e: np.dtype(np.int64),
    }
    if ttl:
        cfg["ttl_micros"] = ttl
    op = UpdatingAggregate(cfg)
    ti = TaskInfo("j", "upd", "updating_aggregate", 0, 1)
    ctx = OperatorContext(ti, None, TableManager(ti, storage))
    return op, cfg, ctx, FakeCollector()


def keyed_batch(ts, users, vals, retracts=None):
    u = np.array(users, dtype=object)
    cols = {
        TIMESTAMP_FIELD: np.array(ts, dtype=np.int64),
        "u": u,
        "v": np.array(vals, dtype=np.int64),
        "_key": hash_columns([u]),
    }
    if retracts is not None:
        cols[IS_RETRACT_FIELD] = np.array(retracts, dtype=bool)
    return Batch(cols)


def test_retract_append_stream():
    op, _cfg, ctx, col = make_op()
    op.process_batch(keyed_batch([0, 1], ["a", "a"], [1, 2]), ctx, col)
    op.handle_watermark(Watermark.event_time(1), ctx, col)
    rows = rows_of(col)
    # first flush: single append
    assert len(rows) == 1
    assert rows[0]["u"] == "a" and rows[0]["cnt"] == 2 and rows[0]["total"] == 3
    assert rows[0][IS_RETRACT_FIELD] is False
    op.process_batch(keyed_batch([2], ["a"], [10]), ctx, col)
    op.handle_watermark(Watermark.event_time(2), ctx, col)
    rows = rows_of(col)
    # second flush: retract old value, append new
    assert len(rows) == 3
    assert rows[1][IS_RETRACT_FIELD] is True and rows[1]["cnt"] == 2 and rows[1]["total"] == 3
    assert rows[2][IS_RETRACT_FIELD] is False and rows[2]["cnt"] == 3 and rows[2]["total"] == 13
    # materialized view has exactly one live row
    final = merge_updating_rows(rows)
    assert final == [{"u": "a", "cnt": 3, "total": 13}]


def test_noop_update_suppressed():
    op, _cfg, ctx, col = make_op(aggs=[("mx", "max", Col("v"))])
    op.process_batch(keyed_batch([0], ["a"], [5]), ctx, col)
    op.handle_watermark(Watermark.event_time(1), ctx, col)
    op.process_batch(keyed_batch([2], ["a"], [3]), ctx, col)  # max unchanged
    op.handle_watermark(Watermark.event_time(3), ctx, col)
    rows = rows_of(col)
    assert len(rows) == 1  # no retract/append pair for the unchanged max


def test_updating_input_retraction():
    op, _cfg, ctx, col = make_op()
    op.process_batch(keyed_batch([0, 0], ["a", "a"], [1, 2]), ctx, col)
    op.handle_watermark(Watermark.event_time(0), ctx, col)
    # retract the v=2 row (e.g. upstream updating join removed it)
    op.process_batch(keyed_batch([1], ["a"], [2], retracts=[True]), ctx, col)
    op.handle_watermark(Watermark.event_time(1), ctx, col)
    final = merge_updating_rows(rows_of(col))
    assert final == [{"u": "a", "cnt": 1, "total": 1}]


def test_retract_to_zero_deletes_key():
    op, _cfg, ctx, col = make_op()
    op.process_batch(keyed_batch([0], ["a"], [7]), ctx, col)
    op.handle_watermark(Watermark.event_time(0), ctx, col)
    op.process_batch(keyed_batch([1], ["a"], [7], retracts=[True]), ctx, col)
    op.handle_watermark(Watermark.event_time(1), ctx, col)
    assert merge_updating_rows(rows_of(col)) == []
    assert op.state == {}


def test_min_over_updating_input_rejected():
    op, _cfg, ctx, col = make_op(aggs=[("mn", "min", Col("v"))])
    with pytest.raises(ValueError, match="invertible"):
        op.process_batch(keyed_batch([0], ["a"], [1], retracts=[True]), ctx, col)


def test_ttl_eviction_emits_retraction():
    op, _cfg, ctx, col = make_op(ttl=1000)
    op.process_batch(keyed_batch([0], ["a"], [1]), ctx, col)
    op.handle_watermark(Watermark.event_time(0), ctx, col)
    assert len(rows_of(col)) == 1
    # advance far past ttl; key a evicted with a retraction
    op.process_batch(keyed_batch([10_000], ["b"], [2]), ctx, col)
    op.handle_watermark(Watermark.event_time(10_000), ctx, col)
    final = merge_updating_rows(rows_of(col))
    assert final == [{"u": "b", "cnt": 1, "total": 2}]


def test_updating_checkpoint_restore(tmp_path):
    storage = str(tmp_path / "upd")
    op, cfg, _ctx, col = make_op(storage=storage)
    ti = TaskInfo("j", "upd", "updating_aggregate", 0, 1)
    tm = TableManager(ti, storage)
    ctx = OperatorContext(ti, None, tm)
    op.process_batch(keyed_batch([0, 1], ["a", "b"], [1, 2]), ctx, col)
    op.handle_watermark(Watermark.event_time(1), ctx, col)  # flush -> emitted set
    op.handle_checkpoint(None, ctx, col)
    tm.checkpoint(1, 1)

    op2 = UpdatingAggregate(cfg)
    tm2 = TableManager(ti, storage)
    tm2.restore(1, op2.tables())
    ctx2 = OperatorContext(ti, None, tm2)
    col2 = FakeCollector()
    op2.on_start(ctx2)
    op2.process_batch(keyed_batch([2], ["a"], [10]), ctx2, col2)
    op2.handle_watermark(Watermark.event_time(2), ctx2, col2)
    rows = rows_of(col2)
    # restored `emitted` state means the new value retracts the OLD emission
    assert len(rows) == 2
    assert rows[0][IS_RETRACT_FIELD] is True and rows[0]["cnt"] == 1 and rows[0]["total"] == 1
    assert rows[1][IS_RETRACT_FIELD] is False and rows[1]["cnt"] == 2 and rows[1]["total"] == 11
