"""Updating aggregate: retract/append emission, no-op suppression, TTL
eviction, updating input retractions, checkpoint/restore."""

import numpy as np
import pytest

from arroyo_tpu.batch import Batch, TIMESTAMP_FIELD
from arroyo_tpu.expr import Col
from arroyo_tpu.hashing import hash_columns
from arroyo_tpu.operators.base import OperatorContext
from arroyo_tpu.operators.updating_aggregate import (
    IS_RETRACT_FIELD,
    UpdatingAggregate,
    merge_updating_rows,
)
from arroyo_tpu.state.tables import TableManager
from arroyo_tpu.types import TaskInfo, Watermark


class FakeCollector:
    def __init__(self):
        self.batches = []

    def collect(self, b):
        self.batches.append(b)

    def broadcast(self, s):
        pass


def rows_of(col):
    out = []
    for b in col.batches:
        out.extend(b.to_pylist())
    return out




def _ctx_collector(storage):
    ti = TaskInfo("updev", "agg", "agg", 0, 1)
    return OperatorContext(ti, None, TableManager(ti, storage)), FakeCollector()


def make_op(aggs=None, ttl=None, storage="/tmp/upd-agg-unused"):
    cfg = {
        "key_fields": ["u"],
        "aggregates": aggs or [("cnt", "count", None), ("total", "sum", Col("v"))],
        "input_dtype_of": lambda e: np.dtype(np.int64),
    }
    if ttl:
        cfg["ttl_micros"] = ttl
    op = UpdatingAggregate(cfg)
    ti = TaskInfo("j", "upd", "updating_aggregate", 0, 1)
    ctx = OperatorContext(ti, None, TableManager(ti, storage))
    return op, cfg, ctx, FakeCollector()


def keyed_batch(ts, users, vals, retracts=None):
    u = np.array(users, dtype=object)
    cols = {
        TIMESTAMP_FIELD: np.array(ts, dtype=np.int64),
        "u": u,
        "v": np.array(vals, dtype=np.int64),
        "_key": hash_columns([u]),
    }
    if retracts is not None:
        cols[IS_RETRACT_FIELD] = np.array(retracts, dtype=bool)
    return Batch(cols)


def test_retract_append_stream():
    op, _cfg, ctx, col = make_op()
    op.process_batch(keyed_batch([0, 1], ["a", "a"], [1, 2]), ctx, col)
    op.handle_watermark(Watermark.event_time(1), ctx, col)
    rows = rows_of(col)
    # first flush: single append
    assert len(rows) == 1
    assert rows[0]["u"] == "a" and rows[0]["cnt"] == 2 and rows[0]["total"] == 3
    assert rows[0][IS_RETRACT_FIELD] is False
    op.process_batch(keyed_batch([2], ["a"], [10]), ctx, col)
    op.handle_watermark(Watermark.event_time(2), ctx, col)
    rows = rows_of(col)
    # second flush: retract old value, append new
    assert len(rows) == 3
    assert rows[1][IS_RETRACT_FIELD] is True and rows[1]["cnt"] == 2 and rows[1]["total"] == 3
    assert rows[2][IS_RETRACT_FIELD] is False and rows[2]["cnt"] == 3 and rows[2]["total"] == 13
    # materialized view has exactly one live row
    final = merge_updating_rows(rows)
    assert final == [{"u": "a", "cnt": 3, "total": 13}]


def test_noop_update_suppressed():
    op, _cfg, ctx, col = make_op(aggs=[("mx", "max", Col("v"))])
    op.process_batch(keyed_batch([0], ["a"], [5]), ctx, col)
    op.handle_watermark(Watermark.event_time(1), ctx, col)
    op.process_batch(keyed_batch([2], ["a"], [3]), ctx, col)  # max unchanged
    op.handle_watermark(Watermark.event_time(3), ctx, col)
    rows = rows_of(col)
    assert len(rows) == 1  # no retract/append pair for the unchanged max


def test_updating_input_retraction():
    op, _cfg, ctx, col = make_op()
    op.process_batch(keyed_batch([0, 0], ["a", "a"], [1, 2]), ctx, col)
    op.handle_watermark(Watermark.event_time(0), ctx, col)
    # retract the v=2 row (e.g. upstream updating join removed it)
    op.process_batch(keyed_batch([1], ["a"], [2], retracts=[True]), ctx, col)
    op.handle_watermark(Watermark.event_time(1), ctx, col)
    final = merge_updating_rows(rows_of(col))
    assert final == [{"u": "a", "cnt": 1, "total": 1}]


def test_retract_to_zero_deletes_key():
    op, _cfg, ctx, col = make_op()
    op.process_batch(keyed_batch([0], ["a"], [7]), ctx, col)
    op.handle_watermark(Watermark.event_time(0), ctx, col)
    op.process_batch(keyed_batch([1], ["a"], [7], retracts=[True]), ctx, col)
    op.handle_watermark(Watermark.event_time(1), ctx, col)
    assert merge_updating_rows(rows_of(col)) == []
    assert op.state == {}


def test_min_over_updating_input_rejected():
    op, _cfg, ctx, col = make_op(aggs=[("mn", "min", Col("v"))])
    with pytest.raises(ValueError, match="invertible"):
        op.process_batch(keyed_batch([0], ["a"], [1], retracts=[True]), ctx, col)


def test_ttl_eviction_emits_retraction():
    op, _cfg, ctx, col = make_op(ttl=1000)
    op.process_batch(keyed_batch([0], ["a"], [1]), ctx, col)
    op.handle_watermark(Watermark.event_time(0), ctx, col)
    assert len(rows_of(col)) == 1
    # advance far past ttl; key a evicted with a retraction
    op.process_batch(keyed_batch([10_000], ["b"], [2]), ctx, col)
    op.handle_watermark(Watermark.event_time(10_000), ctx, col)
    final = merge_updating_rows(rows_of(col))
    assert final == [{"u": "b", "cnt": 1, "total": 2}]


def test_updating_checkpoint_restore(tmp_path):
    storage = str(tmp_path / "upd")
    op, cfg, _ctx, col = make_op(storage=storage)
    ti = TaskInfo("j", "upd", "updating_aggregate", 0, 1)
    tm = TableManager(ti, storage)
    ctx = OperatorContext(ti, None, tm)
    op.process_batch(keyed_batch([0, 1], ["a", "b"], [1, 2]), ctx, col)
    op.handle_watermark(Watermark.event_time(1), ctx, col)  # flush -> emitted set
    op.handle_checkpoint(None, ctx, col)
    tm.checkpoint(1, 1)

    op2 = UpdatingAggregate(cfg)
    tm2 = TableManager(ti, storage)
    tm2.restore(1, op2.tables())
    ctx2 = OperatorContext(ti, None, tm2)
    col2 = FakeCollector()
    op2.on_start(ctx2)
    op2.process_batch(keyed_batch([2], ["a"], [10]), ctx2, col2)
    op2.handle_watermark(Watermark.event_time(2), ctx2, col2)
    rows = rows_of(col2)
    # restored `emitted` state means the new value retracts the OLD emission
    assert len(rows) == 2
    assert rows[0][IS_RETRACT_FIELD] is True and rows[0]["cnt"] == 1 and rows[0]["total"] == 1
    assert rows[1][IS_RETRACT_FIELD] is False and rows[1]["cnt"] == 2 and rows[1]["total"] == 11


def test_device_mode_matches_host_mode(tmp_path, _storage):
    """The device-lowered updating aggregate (signed scatter lanes + flush
    gather) must emit exactly what the host dict path emits, including
    retract/append pairs, no-op suppression, and TTL evictions."""
    import numpy as np

    from arroyo_tpu.batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
    from arroyo_tpu.hashing import hash_columns
    from arroyo_tpu.operators.updating_aggregate import (
        UpdatingAggregate,
        merge_updating_rows,
    )
    from arroyo_tpu.expr import Col

    def run(backend):
        op = UpdatingAggregate({
            "key_fields": ["k"],
            "aggregates": [("n", "count", None), ("total", "sum", Col("v")),
                           ("mean", "avg", Col("v"))],
            "input_dtype_of": lambda e: np.dtype(np.int64),
            "ttl_micros": 30_000_000,
            "backend": backend,
        })
        if backend == "jax":
            assert op.device_mode
        else:
            assert not op.device_mode
        ctx, col = _ctx_collector(str(tmp_path / backend))
        rng = np.random.default_rng(31)
        out = []
        for step in range(8):
            n = 200
            # keys 6-11 go idle after step 3 so the TTL eviction branch
            # fires (retractions for evicted keys) in both modes
            hi = 12 if step < 4 else 6
            ks = rng.integers(0, hi, size=n).astype(np.int64)
            vs = rng.integers(1, 100, size=n).astype(np.int64)
            ts = np.full(n, step * 10_000_000, dtype=np.int64)
            op.process_batch(Batch({
                "k": ks, "v": vs, TIMESTAMP_FIELD: ts,
                KEY_FIELD: hash_columns([ks]),
            }), ctx, col)
            op.handle_tick(ctx, col)
            out.extend(r for b in col.batches for r in b.to_pylist())
            col.batches.clear()
        op.on_close(ctx, col)
        out.extend(r for b in col.batches for r in b.to_pylist())
        return merge_updating_rows(out)

    canon = lambda rows: sorted(
        (r["k"], r["n"], r["total"], round(float(r["mean"]), 9)) for r in rows
    )
    host = canon(run("numpy"))
    dev = canon(run("jax"))
    assert dev == host
    # keys 6-11 went idle past the TTL: evicted with retractions, so only
    # the still-active 6 keys survive the merge — in BOTH modes
    assert len(dev) == 6 and {k for k, *_ in dev} == set(range(6))


def test_device_mode_checkpoint_restore(tmp_path, _storage):
    """Device-mode snapshot -> restore preserves accumulators, emitted cache
    (no spurious re-appends) and TTL clocks."""
    import numpy as np

    from arroyo_tpu.batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
    from arroyo_tpu.hashing import hash_columns
    from arroyo_tpu.operators.updating_aggregate import (
        UpdatingAggregate,
        merge_updating_rows,
    )
    from arroyo_tpu.expr import Col
    from arroyo_tpu.types import CheckpointBarrier

    cfg_op = {
        "key_fields": ["k"],
        "aggregates": [("n", "count", None), ("total", "sum", Col("v"))],
        "input_dtype_of": lambda e: np.dtype(np.int64),
        "backend": "jax",
    }
    op = UpdatingAggregate(cfg_op)
    ctx, col = _ctx_collector(str(tmp_path))
    ks = np.arange(6, dtype=np.int64) % 3
    vs = (np.arange(6, dtype=np.int64) + 1) * 10
    b = Batch({"k": ks, "v": vs, TIMESTAMP_FIELD: np.full(6, 1000, dtype=np.int64),
               KEY_FIELD: hash_columns([ks])})
    op.process_batch(b, ctx, col)
    op.handle_checkpoint(CheckpointBarrier(1, 1, 0, False), ctx, col)
    pre = [r for bb in col.batches for r in bb.to_pylist()]

    op2 = UpdatingAggregate(cfg_op)
    ctx2, col2 = _ctx_collector(str(tmp_path))
    ctx2.table_manager = ctx.table_manager
    op2.on_start(ctx2)
    # same keys again: restored accumulators continue, restored emitted cache
    # produces retract/append pairs (not bare appends)
    op2.process_batch(b, ctx2, col2)
    op2.on_close(ctx2, col2)
    post = [r for bb in col2.batches for r in bb.to_pylist()]
    final = merge_updating_rows(pre + post)
    got = sorted((r["k"], r["n"], r["total"]) for r in final)
    assert got == [(0, 4, 2 * (10 + 40)), (1, 4, 2 * (20 + 50)), (2, 4, 2 * (30 + 60))]


def test_count_distinct_retracts_and_checkpoint_roundtrip(tmp_path):
    """COUNT(DISTINCT) over a retracting input: the per-value multiplicity
    map inverts exactly, survives the JSON checkpoint encoding, and keeps
    counting correctly after restore."""
    storage = str(tmp_path / "cd-ckpt")
    aggs = [("d", "count_distinct", Col("v")), ("cnt", "count", None)]
    op, cfg, ctx, col = make_op(aggs=aggs, storage=storage)
    # key a sees values 1,1,2 (distinct 2); retract one of the 1s -> still 2
    op.process_batch(keyed_batch([0, 1, 2, 3], ["a"] * 4, [1, 1, 2, 1],
                                 retracts=[False, False, False, True]), ctx, col)
    op._flush(col)
    r = [x for x in rows_of(col) if not x[IS_RETRACT_FIELD]][-1]
    assert r["d"] == 2 and r["cnt"] == 2
    # retract the remaining 1 -> distinct drops to 1
    op.process_batch(keyed_batch([4], ["a"], [1], retracts=[True]), ctx, col)
    op._flush(col)
    r = [x for x in rows_of(col) if not x[IS_RETRACT_FIELD]][-1]
    assert r["d"] == 1 and r["cnt"] == 1

    # checkpoint with a live multi-entry map, restore, keep mutating
    op.process_batch(keyed_batch([5, 6], ["a", "a"], [7, 8]), ctx, col)
    op.handle_checkpoint(None, ctx, col)
    ctx.table_manager.checkpoint(1, None)

    op2 = UpdatingAggregate(cfg | {"aggregates": aggs})
    ti = TaskInfo("j", "upd", "updating_aggregate", 0, 1)
    tm2 = TableManager(ti, storage)
    tm2.restore(1, op2.tables())
    ctx2 = OperatorContext(ti, None, tm2)
    col2 = FakeCollector()
    op2.on_start(ctx2)
    # retract value 7 (from before the checkpoint) and add two new values:
    # the map restored from JSON must honor the retraction exactly
    op2.process_batch(keyed_batch([7, 8, 9], ["a", "a", "a"], [7, 9, 10],
                                  retracts=[True, False, False]), ctx2, col2)
    op2._flush(col2)
    r = [x for x in rows_of(col2) if not x[IS_RETRACT_FIELD]][-1]
    # live values now {2, 8, 9, 10} -> distinct 4, count 4
    assert r["d"] == 4 and r["cnt"] == 4
