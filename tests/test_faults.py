"""Fault injection subsystem + shared retry layer + chaos recovery proofs.

Covers: plan parsing and deterministic firing (faults/plan.py), the shared
backoff/deadline/budget/breaker layer (utils/retry.py), storage ops
recovering through injected transient faults without a job restart,
crash-consistent compaction torn at every interesting point, commit-deferred
RabbitMQ acks under a mid-checkpoint crash, Kinesis reshard pickup with
stable shard assignment under poll faults, and controller behavior under
induced worker crashes (restart-budget exhaustion -> Failed, heartbeat
starvation -> detected + recovered). The byte-exact golden recovery runs
live in test_smoke.py's chaos axis.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from arroyo_tpu import faults
from arroyo_tpu.faults import InjectedFault, InjectedPartition, PlanSyntaxError
from arroyo_tpu.utils import retry as retry_mod
from arroyo_tpu.utils.retry import (
    Backoff,
    CircuitBreaker,
    CircuitOpenError,
    RetryBudget,
    RetryPolicy,
    retry_call,
)

SMOKE = os.path.join(os.path.dirname(__file__), "smoke")


# ------------------------------------------------------------ plan grammar


def test_plan_parsing_and_errors():
    specs = faults.parse_plan(
        "storage.put:fail_once@epoch=2, network.send:drop@step=40,"
        "worker:crash@barrier=3&step=1, queue.put:delay=50@after=2,"
        "storage.put:fail_n=3@match=compacted"
    )
    assert [s.site for s in specs] == [
        "storage.put", "network.send", "worker", "queue.put", "storage.put"]
    assert specs[0].action == "fail_once" and specs[0].conds == {"epoch": "2"}
    assert specs[3].action == "delay" and specs[3].arg == 50.0
    assert specs[4].action == "fail_n" and specs[4].arg == 3.0

    with pytest.raises(PlanSyntaxError, match="site:action"):
        faults.parse_plan("nonsense")
    with pytest.raises(PlanSyntaxError, match="unknown action"):
        faults.parse_plan("storage.put:explode")
    with pytest.raises(PlanSyntaxError, match="needs =ARG"):
        faults.parse_plan("queue.put:delay")
    with pytest.raises(PlanSyntaxError, match="bad condition"):
        faults.parse_plan("storage.put:fail@oops")


def test_injector_counters_and_ordinals():
    inj = faults.install("storage.put:fail_once@match=ckpt,"
                         "network.send:drop@step=2", seed=1)
    # non-matching key: no fire, no hit
    assert faults.fault_point("storage.put", key="other") is None
    with pytest.raises(InjectedFault):
        faults.fault_point("storage.put", key="a/ckpt/b")
    # fail_once: second matching hit passes clean
    assert faults.fault_point("storage.put", key="a/ckpt/b") is None
    # step=2 fires on exactly the second hit
    assert faults.fault_point("network.send", key="q") is None
    assert faults.fault_point("network.send", key="q") == ("drop", None)
    assert faults.fault_point("network.send", key="q") is None
    assert len(inj.fired_log) == 2


def test_injector_partition_and_crash_types():
    faults.install("network.send:partition@step=1,worker:crash@barrier=7")
    with pytest.raises(ConnectionError):
        faults.fault_point("network.send", key="q")
    # wrong barrier: no fire
    assert faults.fault_point("worker", barrier=6) is None
    with pytest.raises(faults.InjectedCrash):
        faults.fault_point("worker", barrier=7)


def test_injector_seeded_probability_replays():
    def run(seed):
        inj = faults.FaultInjector("connector.poll:fail@prob=0.5", seed=seed)
        fired = []
        for _ in range(64):
            try:
                inj.hit("connector.poll")
                fired.append(0)
            except InjectedFault:
                fired.append(1)
        return fired

    assert run(42) == run(42)          # same seed: identical sequence
    assert run(42) != run(43)          # different seed: different sequence
    assert 10 < sum(run(42)) < 54      # and it is actually probabilistic


def test_fault_point_noop_without_plan():
    faults.clear()
    assert faults.fault_point("storage.put", key="x") is None
    assert faults.active() is None


# ---------------------------------------------------------------- retry.py


def test_retry_call_recovers_transient_and_raises_permanent():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    assert retry_call(flaky, policy=RetryPolicy(base_delay_s=0.001),
                      sleep=lambda s: None) == "ok"
    assert calls["n"] == 3

    def permanent():
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        retry_call(permanent, policy=RetryPolicy(base_delay_s=0.001))


def test_retry_exhaustion_raises_last_error():
    def always():
        raise TimeoutError("still down")

    with pytest.raises(TimeoutError, match="still down"):
        retry_call(always, policy=RetryPolicy(max_attempts=3, base_delay_s=0.001),
                   sleep=lambda s: None)


def test_backoff_growth_jitter_and_deadline():
    b = Backoff(RetryPolicy(max_attempts=100, base_delay_s=0.1, max_delay_s=1.0,
                            multiplier=2.0, jitter=0.0))
    assert [round(b.next_delay(), 3) for _ in range(5)] == [0.1, 0.2, 0.4, 0.8, 1.0]
    jittered = Backoff(RetryPolicy(base_delay_s=0.1, jitter=0.5))
    d = jittered.next_delay()
    assert 0.05 <= d <= 0.1
    deadline = Backoff(RetryPolicy(max_attempts=1000, deadline_s=0.0))
    time.sleep(0.001)
    assert deadline.exhausted()


def test_retry_budget_denies_when_drained():
    budget = RetryBudget(capacity=2, refill_per_s=0.0)
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise ConnectionError("x")

    with pytest.raises(ConnectionError):
        retry_call(always, policy=RetryPolicy(max_attempts=10, base_delay_s=0.001),
                   sleep=lambda s: None, budget=budget)
    assert calls["n"] == 3  # first try + the 2 budgeted retries


def test_circuit_breaker_opens_and_half_opens():
    br = CircuitBreaker(threshold=2, cooldown_s=0.05, name="t")

    def boom():
        raise ConnectionError("x")

    for _ in range(2):
        with pytest.raises(ConnectionError):
            retry_call(boom, policy=RetryPolicy(max_attempts=1), breaker=br)
    assert br.open
    with pytest.raises(CircuitOpenError):
        retry_call(boom, policy=RetryPolicy(max_attempts=1), breaker=br)
    time.sleep(0.06)  # cooldown: a probe is allowed again
    assert retry_call(lambda: "up", breaker=br) == "up"
    assert not br.open


# ----------------------------------------------------- storage under faults


def test_storage_transient_fault_recovers_in_place(tmp_path):
    from arroyo_tpu.state import storage

    p = str(tmp_path / "blob.bin")
    faults.install("storage.put:fail_once@match=blob,storage.get:fail_once@match=blob")
    storage.write_bytes(p, b"payload")       # retried through the fault
    assert storage.read_bytes(p) == b"payload"
    inj = faults.active()
    assert len(inj.fired_log) == 2


def test_storage_permanent_fault_exhausts_and_raises(tmp_path):
    from arroyo_tpu.state import storage

    faults.install("storage.put:fail@match=doomed")
    with pytest.raises(InjectedFault):
        storage.write_bytes(str(tmp_path / "doomed.bin"), b"x")
    faults.clear()
    storage.write_bytes(str(tmp_path / "doomed.bin"), b"x")  # recovers after


def test_queue_put_delay_fault():
    from arroyo_tpu.engine.queues import TaskInbox
    from arroyo_tpu.batch import Batch
    from arroyo_tpu.batch import TIMESTAMP_FIELD

    inbox = TaskInbox(1, 1024)
    faults.install("queue.put:delay=30@step=1")
    b = Batch({TIMESTAMP_FIELD: np.array([1, 2], dtype=np.int64)})
    t0 = time.monotonic()
    inbox.put(0, b)
    assert time.monotonic() - t0 >= 0.025
    assert inbox.get(timeout=1) is not None


def test_network_send_verdicts_unit():
    faults.install("network.send:drop@step=1,network.send:dup@step=2,"
                   "network.send:partition@step=3")
    assert faults.fault_point("network.send", key="(0, 0, 1, 0)") == ("drop", None)
    assert faults.fault_point("network.send", key="(0, 0, 1, 0)") == ("dup", None)
    with pytest.raises(InjectedPartition):
        faults.fault_point("network.send", key="(0, 0, 1, 0)")


# ----------------------------------------- crash-consistent compaction unit


def _make_epoch(url: str, job: str, epoch: int, n_sub: int = 3):
    from arroyo_tpu.batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
    from arroyo_tpu.state.tables import TableManager, write_job_checkpoint_metadata
    from arroyo_tpu.types import TaskInfo

    for sub in range(n_sub):
        tm = TableManager(TaskInfo(job, "op", "op", sub, n_sub), url)
        keys = (np.arange(2, dtype=np.int64) + 10 * sub).view(np.uint64)
        tm.expiring_time_key("t", 10_000_000).insert(Batch({
            TIMESTAMP_FIELD: np.array([0, 1000], dtype=np.int64),
            KEY_FIELD: keys,
            "v": np.array([sub, sub + 100], dtype=np.int64),
        }))
        tm.global_keyed("g").insert(sub, {"off": sub})
        tm.checkpoint(epoch=epoch, watermark_micros=None)
    write_job_checkpoint_metadata(url, job, epoch)


def _restore_rows(url: str, job: str, epoch: int):
    from arroyo_tpu.state.tables import TableManager
    from arroyo_tpu.types import TaskInfo
    from arroyo_tpu.operators.base import TableSpec

    tm = TableManager(TaskInfo(job, "op", "op", 0, 1), url)
    tm.restore(epoch, [TableSpec("t", "expiring_time_key", 10_000_000),
                       TableSpec("g", "global_keyed")])
    rows = sorted(int(v) for b in tm.expiring_time_key("t").all_batches()
                  for v in b["v"])
    globs = dict(tm.global_keyed("g").items())
    return rows, globs


EXPECT_ROWS = [0, 1, 2, 100, 101, 102]
EXPECT_GLOBS = {0: {"off": 0}, 1: {"off": 1}, 2: {"off": 2}}


@pytest.mark.chaos
@pytest.mark.parametrize("tear_after", [1, 2, 3])
def test_compaction_torn_at_each_metadata_write_restores_exact(tmp_path, tear_after):
    """Kill the metadata rewrite after each of the 3 writes (the first is
    the g1 commit point): restore must produce identical state either side
    of the commit point — no loss, no double-counted rows."""
    from arroyo_tpu.state.tables import compact_job

    url = str(tmp_path / "ckpt")
    _make_epoch(url, "j", 2)
    faults.install(f"storage.put:fail@match=metadata-&after={tear_after}")
    with pytest.raises(InjectedFault):
        compact_job(url, "j", 2)
    faults.clear()
    rows, globs = _restore_rows(url, "j", 2)
    assert rows == EXPECT_ROWS
    assert globs == EXPECT_GLOBS


@pytest.mark.chaos
def test_compaction_rerun_after_tear_completes_cleanup(tmp_path):
    """Re-running compaction over a torn epoch finishes the cleanup (drops
    stale gen-0 entries + files) instead of re-merging into the live g1
    file; the epoch stays restorable throughout."""
    from arroyo_tpu.state import storage
    from arroyo_tpu.state.tables import compact_job, operator_dir

    url = str(tmp_path / "ckpt")
    _make_epoch(url, "j", 2)
    faults.install("storage.put:fail@match=metadata-&after=2")
    with pytest.raises(InjectedFault):
        compact_job(url, "j", 2)
    faults.clear()

    opdir = operator_dir(url, "j", 2, "op")
    stale_before = [fn for fn in storage.listdir(opdir)
                    if fn.startswith("table-") and "compacted" not in fn]
    assert stale_before, "tear should leave gen-0 shards on disk"
    compact_job(url, "j", 2)  # resume: cleanup only
    metas = [json.loads(storage.read_text(os.path.join(opdir, fn)))
             for fn in storage.listdir(opdir) if fn.startswith("metadata-")]
    gen0 = [fm for m in metas for fm in m["files"]
            if int(fm.get("generation", 0)) == 0]
    assert not gen0, "resume must drop every stale gen-0 entry"
    rows, globs = _restore_rows(url, "j", 2)
    assert rows == EXPECT_ROWS
    assert globs == EXPECT_GLOBS


@pytest.mark.chaos
def test_compaction_torn_at_delete_step_sweeps_orphans(tmp_path):
    """Tear AFTER all metadata rewrites but during shard deletion: the
    de-listed gen-0 files are orphans no metadata references; a compaction
    re-run must sweep them (restore is already correct either way)."""
    from arroyo_tpu.state import storage
    from arroyo_tpu.state.tables import compact_job, operator_dir

    url = str(tmp_path / "ckpt")
    _make_epoch(url, "j", 2)
    faults.install("storage.delete:fail@match=table-")
    with pytest.raises(InjectedFault):
        compact_job(url, "j", 2)
    faults.clear()
    rows, globs = _restore_rows(url, "j", 2)
    assert rows == EXPECT_ROWS and globs == EXPECT_GLOBS
    compact_job(url, "j", 2)  # resume: orphan sweep only
    opdir = operator_dir(url, "j", 2, "op")
    leftovers = [fn for fn in storage.listdir(opdir)
                 if fn.startswith("table-") and "compacted-g1" not in fn]
    assert not leftovers, leftovers
    rows, globs = _restore_rows(url, "j", 2)
    assert rows == EXPECT_ROWS and globs == EXPECT_GLOBS


def test_compaction_clean_path_still_exact(tmp_path):
    from arroyo_tpu.state.tables import compact_job

    url = str(tmp_path / "ckpt")
    _make_epoch(url, "j", 2)
    assert compact_job(url, "j", 2) > 0
    rows, globs = _restore_rows(url, "j", 2)
    assert rows == EXPECT_ROWS
    assert globs == EXPECT_GLOBS


# ------------------------------------------------------- gcs token lifecycle


class _FakeGcsHttp:
    """urlopen stand-in: serves metadata tokens and one object, enforcing
    bearer auth with server-side rotation."""

    def __init__(self):
        self.token = "t1"
        self.expires_in = 3600
        self.token_fetches = 0

    def __call__(self, req, timeout=None):
        import io
        import urllib.error

        url = req.full_url
        if "metadata.google.internal" in url:
            self.token_fetches += 1
            body = json.dumps({"access_token": self.token,
                               "expires_in": self.expires_in}).encode()
            return _resp(io.BytesIO(body))
        auth = req.headers.get("Authorization", "")
        if auth != f"Bearer {self.token}":
            raise urllib.error.HTTPError(url, 401, "unauthorized", {}, io.BytesIO(b""))
        return _resp(io.BytesIO(b"object-bytes"))


def _resp(bio):
    class R:
        def __enter__(self):
            return bio

        def __exit__(self, *a):
            return False

    return R()


def test_gcs_token_refresh_and_401_retry(monkeypatch):
    import urllib.request

    from arroyo_tpu.state.storage import GcsHttpClient

    fake = _FakeGcsHttp()
    monkeypatch.delenv("GOOGLE_OAUTH_ACCESS_TOKEN", raising=False)
    monkeypatch.setattr(urllib.request, "urlopen", fake)
    client = GcsHttpClient(endpoint="https://fake-gcs")

    assert client.download("b", "o") == b"object-bytes"
    assert fake.token_fetches == 1
    assert client._token == "t1" and client._token_expiry is not None

    # near-expiry: the next call re-fetches BEFORE the server would 401
    client._token_expiry = time.monotonic() + 1  # inside the refresh margin
    fake.token = "t2"
    assert client.download("b", "o") == b"object-bytes"
    assert fake.token_fetches == 2 and client._token == "t2"

    # surprise server-side rotation (expiry not yet reached): 401 -> refresh
    # once -> retried request succeeds
    fake.token = "t3"
    assert client.download("b", "o") == b"object-bytes"
    assert fake.token_fetches == 3 and client._token == "t3"


# --------------------------------------------- rabbitmq acks under a crash


@pytest.mark.chaos
def test_rabbitmq_no_acks_when_crash_precedes_commit(_storage):
    """The broker must see ZERO acks if the worker dies mid-checkpoint:
    delivery tags are staged per epoch and only ack on the engine's commit.
    (Barrier-time acking — the old behavior — acked here and lost data.)"""
    from test_broker_connectors import MiniRabbit

    from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    broker = MiniRabbit()
    broker.start()
    rows: list = []
    S = Schema.of([("v", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "rabbitmq", "host": "127.0.0.1", "port": broker.port,
        "queue": "in", "format": "json",
        "schema": Schema.of([("v", "int64")])}, 1))
    g.add_node(Node("snk", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "snk", EdgeType.FORWARD, S)
    eng = Engine(g, job_id="rmq-chaos")
    eng.start()
    try:
        deadline = time.monotonic() + 20
        while not broker.consumers and time.monotonic() < deadline:
            time.sleep(0.05)
        assert broker.consumers, "source never consumed"
        for i in range(10):
            broker.publish("in", json.dumps({"v": i}).encode())
        deadline = time.monotonic() + 30
        while len(rows) < 10 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(rows) == 10

        faults.install("worker:crash@barrier=1&step=1")
        with pytest.raises(RuntimeError, match="injected"):
            if eng.checkpoint_and_wait(1, timeout=30):
                raise AssertionError("checkpoint completed despite crash")
            eng.join(timeout=30)
        # the crash happened after state was written but before the commit:
        # nothing may have been acked, so the broker redelivers on reconnect
        assert broker.acked == []
    finally:
        faults.clear()
        eng.stop()
        try:
            eng.join(timeout=30)
        except RuntimeError:
            pass
        broker.close()


# --------------------------------------- kinesis reshard + injected faults


@pytest.mark.chaos
def test_kinesis_reshard_pickup_under_poll_faults(_storage):
    """Child shards appearing mid-run are picked up by the periodic re-list
    even though the subtask still has healthy open shards (the old code
    only re-listed once everything closed), while injected poll faults
    recover through the shared backoff. Exactly the published records
    arrive — no loss, no duplicates."""
    from test_broker_connectors import MiniKinesis

    from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    srv = MiniKinesis(n_shards=1)
    srv.start()
    out: list = []
    for i in range(10):
        srv.put(json.dumps({"counter": i}).encode())
    S = Schema.of([("counter", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "kinesis", "stream_name": "s1",
        "endpoint": f"http://127.0.0.1:{srv.port}",
        "aws_access_key_id": "AK", "aws_secret_access_key": "SK",
        "format": "json", "poll_interval_s": 0.05, "shard_poll_gap_s": 0.05,
        "reshard_interval_s": 0.3,
        "schema": Schema.of([("counter", "int64")])}, 1))
    g.add_node(Node("snk", OpName.SINK, {"connector": "vec", "rows": out}, 1))
    g.add_edge("src", "snk", EdgeType.FORWARD, S)
    faults.install("connector.poll:fail_n=3")
    eng = Engine(g, job_id="kin-chaos")
    eng.start()
    try:
        deadline = time.monotonic() + 30
        while len(out) < 10 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sorted(r["counter"] for r in out) == list(range(10))
        # reshard: a child shard appears while shard 0 stays open
        srv.shards["shardId-000000000001"] = []
        for i in range(10, 20):
            srv.put(json.dumps({"counter": i}).encode(),
                    shard="shardId-000000000001")
        deadline = time.monotonic() + 30
        while len(out) < 20 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sorted(r["counter"] for r in out) == list(range(20))
    finally:
        faults.clear()
        eng.stop()
        eng.join(timeout=30)
        srv.close()


def test_kinesis_stable_assignment_is_disjoint_and_total():
    from arroyo_tpu.connectors.kinesis import shard_owner

    shards = [f"shardId-{i:012d}" for i in range(16)]
    for par in (1, 2, 3, 5):
        owners = {s: shard_owner(s, par) for s in shards}
        assert set(owners.values()) <= set(range(par))
        # stability: adding shards never moves existing assignments
        more = shards + [f"shardId-{i:012d}" for i in range(16, 24)]
        assert all(shard_owner(s, par) == owners[s] for s in shards)
        assert len(more) == len(set(more))


# ----------------------------------------------- controller under failures


def _sql(tmp_path, name="grouped_aggregates"):
    with open(os.path.join(SMOKE, "queries", f"{name}.sql")) as f:
        sql = f.read()
    out = str(tmp_path / "out.json")
    return sql.replace("$input_dir", os.path.join(SMOKE, "inputs")).replace(
        "$output_path", out
    ), out


@pytest.mark.chaos
def test_controller_restart_budget_exhaustion_goes_failed(tmp_path, _storage):
    """Workers that crash at every checkpoint burn the restart budget; the
    job must land in Failed with the budget named — not hang in a
    recover/crash loop forever."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler

    sql, _out = _sql(tmp_path)
    db = Database()
    cfg.update({
        "testing.source-read-delay-micros": 4000,
        "checkpoint.interval-ms": 100,
        "pipeline.allowed-restarts": 1,
        # config-driven plan: every worker incarnation re-arms the crash
        # (step=1 = the first checkpoint of ANY epoch, so the restarted
        # worker — which checkpoints at a later epoch — crashes again)
        "faults.plan": "worker:crash@step=1",
    })
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        state = ctl.wait_for_state(jid, "Failed", timeout=120)
        assert state == "Failed"
        job = db.get_job(jid)
        assert "exceeded allowed-restarts=1" in (job["failure_message"] or "")
        # the DB snapshot lags the in-memory counter by the final failed
        # incarnation; >=1 persisted restart plus the exceeded message
        # together prove the budget was burned down
        assert int(job["restarts"]) >= 1
    finally:
        cfg.update({"testing.source-read-delay-micros": 0,
                    "checkpoint.interval-ms": 10_000,
                    "faults.plan": ""})
        ctl.stop()


@pytest.mark.chaos
def test_controller_heartbeat_timeout_detects_hung_worker(tmp_path, _storage):
    """A worker that stops heartbeating without exiting must be declared
    lost by the heartbeat timeout and replaced; once heartbeats resume the
    job completes with golden output."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import ProcessScheduler

    sql, out = _sql(tmp_path)
    db = Database()
    os.environ["ARROYO_TPU__FAULTS__PLAN"] = "worker.heartbeat:drop@after=1"
    # 400 input lines x 50ms keeps the silent worker alive (~20s) well past
    # the 8s heartbeat timeout; the cured restart drops the delay to zero
    os.environ["ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS"] = "50000"
    os.environ["ARROYO_TPU__CHECKPOINT__STORAGE_URL"] = cfg.config().get(
        "checkpoint.storage-url")
    # longer than worker startup (~4s of jax import) so only true heartbeat
    # silence trips it
    cfg.update({"pipeline.worker-heartbeat-timeout-ms": 8000,
                "checkpoint.interval-ms": 60_000})
    ctl = ControllerServer(db, ProcessScheduler()).start()
    try:
        pid = db.create_pipeline("agg", sql, 1)
        jid = db.create_job(pid)
        # detection: the silent worker is killed and the job recovers
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            job = db.get_job(jid)
            if job and int(job["restarts"] or 0) >= 1:
                break
            time.sleep(0.1)
        job = db.get_job(jid)
        assert int(job["restarts"] or 0) >= 1, "hung worker never detected"
        assert "heartbeat" in (job["failure_message"] or "")
        # cure the fault: the replacement worker heartbeats and finishes
        os.environ.pop("ARROYO_TPU__FAULTS__PLAN", None)
        os.environ["ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS"] = "0"
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        assert os.path.exists(out) or any(
            os.path.exists(out + f".{i}") for i in range(4))
    finally:
        for var in ("ARROYO_TPU__FAULTS__PLAN",
                    "ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS",
                    "ARROYO_TPU__CHECKPOINT__STORAGE_URL"):
            os.environ.pop(var, None)
        cfg.update({"pipeline.worker-heartbeat-timeout-ms": 30_000,
                    "checkpoint.interval-ms": 10_000})
        ctl.stop()


@pytest.mark.chaos
def test_node_admission_fault_surfaces_as_500(tmp_path, _storage):
    """An injected admission failure on the node daemon returns HTTP 500 to
    the scheduler (placement retries are the LazyNodeWorkerHandle's job)."""
    import urllib.error
    import urllib.request

    from arroyo_tpu.controller.node import NodeServer, _post

    # node registration needs an API; run one
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import Database

    db = Database()
    api = ApiServer(db, port=0).start()
    node = NodeServer(f"http://127.0.0.1:{api.port}", slots=2).start()
    faults.install("node.start_worker:fail_once")
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{node.port}/start_worker",
                  {"sql": "SELECT 1", "job_id": "j1", "parallelism": 1})
        assert ei.value.code == 500
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{node.port}/status").read())
        assert st["used"] == 0, "failed admission must not leak a slot"
    finally:
        faults.clear()
        node.stop()
        api.stop()


# ---------------------------------------- controller 2PC + control-plane RPC


def test_controller_rpc_fault_drops_and_recovers_event_polls(_storage):
    """A dropped controller->node event poll loses nothing: the daemon only
    drains its buffer when a poll actually arrives, so the next poll
    catches up. (Wire-level unit test against a stubbed daemon.)"""
    from arroyo_tpu.controller.scheduler import NodeWorkerHandle

    h = NodeWorkerHandle.__new__(NodeWorkerHandle)
    h._buffer = []
    h._alive = True
    h._hb = time.monotonic()
    h.worker_id = "w1"
    h.node_addr = "http://node"
    h.dp_port = None
    calls: list = []

    def fake_get(url, timeout=10.0):
        calls.append(url)
        return {"events": [{"event": "started"}], "alive": True, "hb_age_s": 0.0}

    h._get = fake_get
    faults.install("controller_rpc:drop@op=get&step=1")
    try:
        assert h.poll_events() == []  # dropped poll: the HTTP call never left
        assert calls == []
        assert h.poll_events() == [{"event": "started"}]  # next poll catches up
        assert len(calls) == 1
    finally:
        faults.clear()


def test_controller_rpc_fault_dup_and_drop_commands(_storage):
    """drop/dup on node-daemon commands: a dropped command sends nothing
    (recovery is protocol-level), a duplicated one posts twice — commit
    delivery is idempotent/cumulative so dup is harmless."""
    from arroyo_tpu.controller.scheduler import NodeWorkerHandle

    h = NodeWorkerHandle.__new__(NodeWorkerHandle)
    h._buffer = []
    h._alive = True
    h._hb = time.monotonic()
    h.worker_id = "w1"
    h.node_addr = "http://node"
    h.dp_port = None
    posts: list = []
    h._post = lambda url, body, timeout=10.0: posts.append((url, body)) or {}
    faults.install("controller_rpc:drop@op=post&step=1,"
                   "controller_rpc:dup@op=post&step=2")
    try:
        h.send_commit(3)  # dropped: nothing on the wire
        assert posts == []
        h.send_commit(4)  # duplicated: posted twice
        assert len(posts) == 2 and all(b["epoch"] == 4 for _u, b in posts)
        h.send_commit(5)  # clean
        assert len(posts) == 3
    finally:
        faults.clear()


@pytest.mark.chaos
def test_dropped_commit_redelivered_next_epoch(tmp_path, _storage):
    """Chaos proof for the `commit` site: every worker's phase-2 commit for
    epoch 1 is dropped; because commit delivery is cumulative, epoch 2's
    commit first delivers epoch 1 — the dropped commit is re-delivered on
    the next epoch, not lost — and the 2PC event log still shows metadata
    durability strictly before every commit send."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler

    with open(os.path.join(SMOKE, "queries", "select_star.sql")) as f:
        sql = f.read()
    out = str(tmp_path / "out.json")
    sql = sql.replace("$input_dir", os.path.join(SMOKE, "inputs")).replace(
        "$output_path", out)
    db = Database()
    cfg.update({
        "controller.workers-per-job": 2,
        "checkpoint.interval-ms": 100,
        "testing.source-read-delay-micros": 4000,
    })
    inj = faults.install("commit:drop@epoch=1", seed=11)
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("sel", sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        jc = ctl.jobs[jid]
        engines = [h.engine for h in jc.handles]
        assert len(engines) == 2
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
    finally:
        faults.clear()
        cfg.update({"controller.workers-per-job": 1,
                    "checkpoint.interval-ms": 10_000,
                    "testing.source-read-delay-micros": 0})
        ctl.stop()
    assert inj.fired_log, "commit drop never fired"
    log = jc.checkpoint_event_log
    assert any(ev[0] == "commit_dropped" and ev[1] == 1 for ev in log), log
    assert not any(ev[0] == "commit_sent" and ev[1] == 1 for ev in log), log
    # re-delivery: epoch 2's commit delivered epoch 1 first, in order
    for eng in engines:
        assert 1 in eng.delivered_commits and 2 in eng.delivered_commits, (
            eng.delivered_commits)
        assert eng.delivered_commits.index(1) < eng.delivered_commits.index(2)
    # ordering invariant still holds for everything that WAS sent
    durable_at = {}
    for i, ev in enumerate(log):
        if ev[0] == "metadata_durable":
            durable_at.setdefault(ev[1], i)
        elif ev[0] in ("commit_sent", "commit_dropped"):
            assert ev[1] in durable_at and durable_at[ev[1]] < i, log
