"""Fused mesh execution: the compiled segment runs INSIDE the sharded
aggregate's shard_map'd program (engine/segment.py mesh path).

With device.mesh-devices > 1 and a mesh-markable segment, each micro-batch
is ONE jitted shard_map dispatch: the traced prefix (projections, key
hashing, watermark taps) runs per-shard and feeds owner bucketing →
all_to_all → sort_reduce/probe_merge without rows ever round-tripping to
the host between projection and state update. These tests prove the three
load-bearing claims on 8 emulated CPU devices:

 - engagement is real (module dispatch counters, not vibes: exactly one
   fused program execution per post-verification micro-batch);
 - output is byte-exact against the same golden files the host path is
   held to, including through checkpoint -> crash -> restore chaos for
   the tumbling AND sliding families;
 - checkpoints are canonical (placement-independent), so a restore onto
   a DIFFERENT mesh width (4 -> 8) replays exactly.
"""

from __future__ import annotations

import pytest

from test_smoke import (CHAOS_SEED, assert_fsck_clean, assert_outputs, build,
                        load_sql)

pytestmark = pytest.mark.mesh


def _mesh_devices():
    import jax

    return len(jax.devices())


@pytest.fixture
def _fused_cfg(_storage):
    """Mesh-fused segment config: 8-way mesh, chaining on, compile floor
    dropped to 1 row (smoke batches are far below the production 8192
    floor), and source/coalesce caps small enough that a run spans several
    micro-batches — the first is host-verified, so a single-batch run
    could never prove the fused path executed."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.engine.segment import reset_mesh_dispatch_counts
    from arroyo_tpu.parallel.sharded_agg import reset_dispatch_counts

    if _mesh_devices() < 8:
        pytest.skip("needs 8 virtual devices (conftest sets XLA_FLAGS)")
    cfg.update({
        "device.mesh-devices": 8, "device.table-capacity": 2048,
        "device.batch-capacity": 512, "device.emit-capacity": 512,
        "device.spill-capacity": 512, "device.max-probes": 32,
        "segment.compile.min-rows": 1,
        "pipeline.chaining.enabled": True,
        "pipeline.source-batch-size": 256,
        "engine.coalesce.max-rows": 256,
    })
    reset_mesh_dispatch_counts()
    reset_dispatch_counts()
    yield
    cfg.update({"device.mesh-devices": 0,
                "pipeline.chaining.enabled": False})


def assert_fused_engaged():
    """The engagement proof: at least one micro-batch ran as the fused
    shard_map program, and every such segment-level dispatch was exactly
    one aggregate-level program execution (no hidden host exchange)."""
    from arroyo_tpu.engine.segment import mesh_dispatch_counts
    from arroyo_tpu.parallel.sharded_agg import dispatch_counts

    seg = mesh_dispatch_counts()
    agg = dispatch_counts()
    assert seg["fused"] > 0, f"fused path never engaged: {seg} / {agg}"
    assert agg["fused_steps"] == seg["fused"], (
        f"fused dispatch mismatch (segment {seg} vs aggregate {agg}): "
        f"a fused batch must be exactly one program execution")


@pytest.mark.parametrize(
    "name", ["tumbling_aggregates", "grouped_aggregates", "sliding_window"])
def test_mesh_fused_golden(name, _fused_cfg, tmp_path):
    """Each window family through the fused program at parallelism 1 (mesh
    replaces host data-parallelism): goldens byte-exact, engagement real."""
    out = str(tmp_path / "out.json")
    eng = build(load_sql(name, out), 1, f"mesh-fused-{name}")
    eng.run_to_completion(timeout=180)
    assert_fused_engaged()
    assert_outputs(name, out)


@pytest.mark.chaos
@pytest.mark.parametrize("name", ["tumbling_aggregates", "sliding_window"])
def test_mesh_fused_chaos_crash_mid_checkpoint(name, _fused_cfg, tmp_path):
    """The smoke suite's worst-case chaos point, on the fused path: crash
    after epoch-2 state files land but before the epoch completes. The
    torn epoch must be ignored, and a restore from epoch 1 — which
    re-fuses on the recompiled (cache-hit) segment — must reproduce the
    host-path goldens byte-exact."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.state.tables import latest_complete_checkpoint

    out = str(tmp_path / "out.json")
    sql = load_sql(name, out)
    job_id = f"mesh-chaos-{name}"
    cfg.update({"testing.source-gate-epochs": 2})
    inj = faults.install("worker:crash@barrier=2&step=1", seed=CHAOS_SEED)
    try:
        eng = build(sql, 1, job_id)
        eng.start()
        assert eng.checkpoint_and_wait(1, timeout=60), "epoch 1 did not complete"
        with pytest.raises(RuntimeError, match="injected"):
            if eng.checkpoint_and_wait(2, timeout=60):
                raise AssertionError("epoch 2 completed despite injected crash")
            eng.join(timeout=60)
    finally:
        faults.clear()
        cfg.update({"testing.source-gate-epochs": 0})
    assert inj.fired_log, "crash fault never fired"
    storage_url = cfg.config().get("checkpoint.storage-url")
    assert latest_complete_checkpoint(storage_url, job_id) == 1

    eng2 = build(sql, 1, job_id, restore_epoch=1)
    eng2.run_to_completion(timeout=180)
    assert_fused_engaged()
    assert_outputs(name, out)
    assert_fsck_clean(job_id)


def test_mesh_resize_restore_4_to_8(_fused_cfg, tmp_path):
    """Mesh-width elasticity: checkpoint on a 4-device mesh, restore onto
    8 devices. The snapshot is canonical (owner placement is never
    persisted), so the wider mesh re-shards it through the same rescale
    merge path a parallelism change takes — output stays byte-exact."""
    from arroyo_tpu import config as cfg

    name = "tumbling_aggregates"
    out = str(tmp_path / "out.json")
    sql = load_sql(name, out)
    job_id = "mesh-resize"
    cfg.update({"device.mesh-devices": 4,
                "testing.source-gate-epochs": 2})
    try:
        eng = build(sql, 1, job_id)
        eng.start()
        assert eng.checkpoint_and_wait(1, timeout=60), "epoch 1 did not complete"
        eng.stop()
        eng.join(timeout=60)
    finally:
        cfg.update({"testing.source-gate-epochs": 0})

    cfg.update({"device.mesh-devices": 8})
    eng2 = build(sql, 1, job_id, restore_epoch=1)
    eng2.run_to_completion(timeout=180)
    assert_fused_engaged()
    assert_outputs(name, out)
    assert_fsck_clean(job_id)
