import numpy as np

from arroyo_tpu.expr import BinOp, Case, Cast, Col, Func, Lit, Neg, Not, eval_expr


COLS = {
    "a": np.array([1, 2, 3, 4], dtype=np.int64),
    "b": np.array([10.0, 20.0, 30.0, 40.0], dtype=np.float64),
    "s": np.array(["x", "y", "x", "z"], dtype=object),
}


def ev(e):
    return eval_expr(e, COLS, 4)


def test_arithmetic_and_comparison():
    assert ev(BinOp("+", Col("a"), Lit(1))).tolist() == [2, 3, 4, 5]
    assert ev(BinOp("*", Col("a"), Col("a"))).tolist() == [1, 4, 9, 16]
    assert ev(BinOp(">", Col("b"), Lit(25.0))).tolist() == [False, False, True, True]
    assert ev(BinOp("==", Col("s"), Lit("x"))).tolist() == [True, False, True, False]
    # SQL integer division truncates
    assert ev(BinOp("/", Col("a"), Lit(2))).tolist() == [0, 1, 1, 2]
    assert ev(BinOp("/", Neg(Col("a")), Lit(2))).tolist() == [0, -1, -1, -2]


def test_boolean_and_case():
    e = BinOp("and", BinOp(">", Col("a"), Lit(1)), Not(BinOp("==", Col("s"), Lit("z"))))
    assert ev(e).tolist() == [False, True, True, False]
    c = Case(((BinOp(">", Col("a"), Lit(2)), Lit(100)),), Lit(0))
    assert ev(c).tolist() == [0, 0, 100, 100]


def test_functions():
    assert ev(Func("abs", (Neg(Col("a")),))).tolist() == [1, 2, 3, 4]
    assert ev(Func("length", (Col("s"),))).tolist() == [1, 1, 1, 1]
    assert ev(Func("concat", (Col("s"), Lit("!")))).tolist() == ["x!", "y!", "x!", "z!"]
    assert ev(Func("upper", (Col("s"),))).tolist() == ["X", "Y", "X", "Z"]
    assert ev(Cast(Col("a"), "float32")).dtype == np.float32
    assert ev(Cast(Col("a"), "string")).tolist() == ["1", "2", "3", "4"]


def test_jnp_matches_numpy():
    import jax.numpy as jnp

    jcols = {k: jnp.asarray(v) for k, v in COLS.items() if k != "s"}
    e = BinOp("+", BinOp("*", Col("a"), Lit(3)), Col("b"))
    np.testing.assert_allclose(np.asarray(e.eval_jnp(jcols)), ev(e))
    f = Func("abs", (BinOp("-", Col("a"), Lit(2)),))
    np.testing.assert_allclose(np.asarray(f.eval_jnp(jcols)), ev(f))


def test_three_valued_comparisons():
    """NULL operands make comparisons NULL (not False) in projections;
    NOT propagates NULL; filter-style coercion still rejects unknowns."""
    cols = {"s": np.array(["a", None, "b"], dtype=object),
            "t": np.array([None, None, "b"], dtype=object)}
    gt = eval_expr(BinOp("==", Col("s"), Lit("a")), cols, 3)
    assert gt.tolist() == [True, None, False]
    assert eval_expr(BinOp("==", Col("s"), Col("t")), cols, 3).tolist() == \
        [None, None, True]
    assert eval_expr(Not(BinOp("==", Col("s"), Lit("a"))), cols, 3).tolist() == \
        [False, None, True]
    # WHERE semantics: unknown filters as False
    assert np.asarray(gt, dtype=bool).tolist() == [True, False, False]


def test_case_over_null_comparison():
    """CASE WHEN <NULL comparison> must treat the unknown as not-taken,
    not crash on the object condition array."""
    cols = {"s": np.array(["a", None, "b"], dtype=object)}
    c = Case(((BinOp("==", Col("s"), Lit("a")), Lit(1)),), Lit(0))
    assert eval_expr(c, cols, 3).tolist() == [1, 0, 0]
