"""Replay-soundness auditor tests (arroyo_tpu.analysis.state_audit).

Four layers:
- per-rule AST fixtures: one positive (fires) and one negative (clean)
  class per LR201-LR204, plus the classification edge shapes (barrier-
  flushed, lazy-memo vs monotone-advance, helper-method resolution);
- waiver grammar: ``# state: ephemeral — why`` / ``# effect: idempotent —
  why`` / ``# lint: waive LR2xx — why``, and the no-justification rule;
- AR008 plan-pass fixtures (duplicate TableSpec names, TTL mismatch);
- the runtime cross-check: drive real operators through a real
  TableManager checkpoint/restore roundtrip on smoke-family-shaped data
  and diff every attribute the auditor classifies as *covered* — the
  static verdict and the engine must agree, in both directions (a
  deliberately-broken restore must make the diff non-empty).
"""

from __future__ import annotations

import numpy as np
import pytest

from arroyo_tpu.analysis import (
    Severity,
    analyze_graph,
    audit_package,
    audit_source,
    render_json,
)
from arroyo_tpu.batch import KEY_FIELD, TIMESTAMP_FIELD, Batch, Schema
from arroyo_tpu.expr import Col
from arroyo_tpu.graph import EdgeType, Graph, Node, OpName
from arroyo_tpu.state.tables import TableManager
from arroyo_tpu.types import CheckpointBarrier, TaskInfo, Watermark

DUMMY = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])


def ids_of(diags):
    return {d.rule_id for d in diags}


def audit(src: str):
    return audit_source(src, "operators/fixture.py")


# ------------------------------------------------------------------- LR201


LR201_BAD = """
from arroyo_tpu.operators.base import Operator, TableSpec

class C(Operator):
    def __init__(self, cfg):
        self._cache = {}

    def tables(self):
        return [TableSpec("t", "global_keyed")]

    def process_batch(self, batch, ctx, collector, input_index=0):
        self._cache[1] = batch

    def on_start(self, ctx):
        ctx.table_manager.global_keyed("t")

    def handle_checkpoint(self, barrier, ctx, collector):
        ctx.table_manager.global_keyed("t").insert(0, 1)
"""


def test_lr201_unregistered_mutable_state_fires():
    diags = audit(LR201_BAD)
    assert "LR201" in ids_of(diags)
    d = next(d for d in diags if d.rule_id == "LR201")
    assert d.severity == Severity.ERROR and "_cache" in d.message


def test_lr201_restored_attr_is_covered():
    src = LR201_BAD.replace(
        "        ctx.table_manager.global_keyed(\"t\")",
        "        self._cache = dict(ctx.table_manager.global_keyed(\"t\").items())",
    )
    assert "LR201" not in ids_of(audit(src))


def test_lr201_helper_method_mutation_counts():
    # the mutation moved into a helper reachable from process_batch: the
    # whole-class closure still sees it
    src = LR201_BAD.replace(
        "        self._cache[1] = batch",
        "        self._grow(batch)",
    ) + """
    def _grow(self, batch):
        self._cache[1] = batch
"""
    assert "LR201" in ids_of(audit(src))


def test_lr201_barrier_flushed_buffer_is_clean():
    src = """
from arroyo_tpu.operators.base import Operator, TableSpec

class Sink(Operator):
    def __init__(self, cfg):
        self.buf = []

    def tables(self):
        return [TableSpec("p", "global_keyed")]

    def is_committing(self):
        return True

    def on_start(self, ctx):
        saved = ctx.table_manager.global_keyed("p").get(0)

    def process_batch(self, batch, ctx, collector, input_index=0):
        self.buf.extend([batch])

    def handle_checkpoint(self, barrier, ctx, collector):
        ctx.table_manager.global_keyed("p").insert(0, list(self.buf))
        self.buf = []
"""
    assert "LR201" not in ids_of(audit(src))


def test_lr201_lazy_memo_clean_but_monotone_advance_fires():
    memo = """
from arroyo_tpu.operators.base import Operator

class C(Operator):
    def __init__(self, cfg):
        self._agg = None

    def process_batch(self, batch, ctx, collector, input_index=0):
        if self._agg is None:
            self._agg = object()
"""
    assert ids_of(audit(memo)) == set()
    # `is None or <progress>` is the monotone-advance shape (the tumbling
    # late-boundary bug): NOT a memo, must fire
    advance = memo.replace(
        "        if self._agg is None:\n            self._agg = object()",
        "        if self._agg is None or batch.num_rows > self._agg:\n"
        "            self._agg = batch.num_rows",
    )
    assert "LR201" in ids_of(audit(advance))


def test_lr201_state_ephemeral_waiver_grammar():
    waived = LR201_BAD.replace(
        "        self._cache = {}",
        "        self._cache = {}  # state: ephemeral — derived per-epoch scratch, rebuilt by replay",
    )
    assert "LR201" not in ids_of(audit(waived))
    # a waiver with no justification text does not suppress
    empty = LR201_BAD.replace(
        "        self._cache = {}",
        "        self._cache = {}  # state: ephemeral",
    )
    assert "LR201" in ids_of(audit(empty))
    # the generic lint-waive form works too, on a mutation line
    generic = LR201_BAD.replace(
        "        self._cache[1] = batch",
        "        self._cache[1] = batch  # lint: waive LR201 — scratch",
    )
    assert "LR201" not in ids_of(audit(generic))


# ------------------------------------------------------------------- LR202


LR202_BAD = """
from arroyo_tpu.operators.base import Operator

class Sink(Operator):
    def __init__(self, cfg):
        self.producer = cfg["producer"]

    def is_committing(self):
        return True

    def process_batch(self, batch, ctx, collector, input_index=0):
        self.producer.produce("topic", batch)

    def handle_commit(self, epoch, ctx):
        pass
"""


def test_lr202_effect_in_hot_path_of_committing_class():
    diags = audit(LR202_BAD)
    assert "LR202" in ids_of(diags)


def test_lr202_effect_under_handle_commit_is_clean():
    src = """
from arroyo_tpu.operators.base import Operator

class Sink(Operator):
    def __init__(self, cfg):
        self.producer = cfg["producer"]
        self.pending = {}

    def is_committing(self):
        return True

    def process_batch(self, batch, ctx, collector, input_index=0):
        self.pending.setdefault(0, []).append(batch)  # state: ephemeral — staged then committed

    def handle_commit(self, epoch, ctx):
        for p in self.pending.pop(epoch, []):
            self.producer.produce("topic", p)
"""
    assert "LR202" not in ids_of(audit(src))


def test_lr202_non_committing_class_is_out_of_scope():
    src = LR202_BAD.replace("return True", "return False")
    assert "LR202" not in ids_of(audit(src))


def test_lr202_idempotent_waiver():
    src = LR202_BAD.replace(
        "        self.producer.produce(\"topic\", batch)",
        "        # effect: idempotent — keyed upsert, replay overwrites\n"
        "        self.producer.produce(\"topic\", batch)",
    )
    assert "LR202" not in ids_of(audit(src))


# ------------------------------------------------------------------- LR203


def test_lr203_written_but_undeclared_table():
    src = """
from arroyo_tpu.operators.base import Operator, TableSpec

class C(Operator):
    def tables(self):
        return [TableSpec("a", "global_keyed")]

    def on_start(self, ctx):
        ctx.table_manager.global_keyed("a").get(0)

    def handle_checkpoint(self, barrier, ctx, collector):
        ctx.table_manager.global_keyed("a").insert(0, 1)
        ctx.table_manager.global_keyed("b").insert(0, 2)
"""
    diags = [d for d in audit(src) if d.rule_id == "LR203"]
    assert any("'b'" in d.message and d.severity == Severity.ERROR
               for d in diags)


def test_lr203_declared_but_unwired_is_warning():
    src = """
from arroyo_tpu.operators.base import Operator, TableSpec

class C(Operator):
    def tables(self):
        return [TableSpec("dead", "global_keyed")]

    def process_batch(self, batch, ctx, collector, input_index=0):
        pass
"""
    diags = [d for d in audit(src) if d.rule_id == "LR203"]
    assert len(diags) == 1 and diags[0].severity == Severity.WARNING


def test_lr203_symmetric_class_is_clean():
    src = """
from arroyo_tpu.operators.base import Operator, TableSpec

class C(Operator):
    def tables(self):
        return [TableSpec("t", "expiring_time_key")]

    def on_start(self, ctx):
        ctx.table_manager.expiring_time_key("t").all_batches()

    def handle_checkpoint(self, barrier, ctx, collector):
        ctx.table_manager.expiring_time_key("t").replace_all([])
"""
    assert "LR203" not in ids_of(audit(src))


# ------------------------------------------- tiered-state spill manifests


SPILL_SOUND = """
from arroyo_tpu.operators.base import Operator, TableSpec
from arroyo_tpu.state.spill import checkpoint_manifest, restore_manifest

class SpillSound(Operator):
    def tables(self):
        return [TableSpec("s__spill", "global_keyed")]

    def on_start(self, ctx):
        self.annex = build_annex(ctx)
        self.annex.adopt(restore_manifest(ctx, "s__spill"))

    def process_batch(self, batch, ctx, collector, input_index=0):
        self.annex.lookup_many([1])
        self.annex.spill(0, [])

    def handle_checkpoint(self, barrier, ctx, collector):
        checkpoint_manifest(ctx, "s__spill", self.annex)
"""


def test_spill_annex_checkpoint_covered_is_clean():
    """The positive half of the manifest pair: annex probed/spilled on the
    hot path, manifest checkpointed at the barrier and re-adopted in
    on_start — covered, symmetric, convention-following."""
    assert not audit(SPILL_SOUND)


def test_spill_annex_unchreckpointed_manifest_fires_lr201():
    """The negative half: the annex mutates on the hot path (probes
    tombstone what they promote; spills move ownership) but nothing ever
    checkpoints or restores its manifest — a restore silently forgets
    which runs exist and every spilled key resurrects stale or vanishes."""
    src = """
from arroyo_tpu.operators.base import Operator

class SpillLeaky(Operator):
    def process_batch(self, batch, ctx, collector, input_index=0):
        self.annex.lookup_many([1, 2])
"""
    diags = audit(src)
    assert any(d.rule_id == "LR201" and "annex" in d.message for d in diags)


def test_spill_manifest_name_convention_fires_lr203():
    """A manifest persisted under a table name without the ``__spill``
    suffix checkpoints fine but is invisible to spill-run GC liveness —
    the convention is enforced, both directions (write and restore)."""
    src = """
from arroyo_tpu.operators.base import Operator, TableSpec
from arroyo_tpu.state.spill import checkpoint_manifest, restore_manifest

class C(Operator):
    def tables(self):
        return [TableSpec("manifest", "global_keyed")]

    def on_start(self, ctx):
        self.annex = build_annex(ctx)
        self.annex.adopt(restore_manifest(ctx, "manifest"))

    def process_batch(self, batch, ctx, collector, input_index=0):
        self.annex.lookup_many([1])

    def handle_checkpoint(self, barrier, ctx, collector):
        checkpoint_manifest(ctx, "manifest", self.annex)
"""
    diags = audit(src)
    hits = [d for d in diags if d.rule_id == "LR203" and "__spill" in d.message]
    assert hits, diags


# ------------------------------------------------------------------- LR204


LR204_BAD = """
from arroyo_tpu.operators.base import Operator

class C(Operator):
    def __init__(self, cfg):
        self.state = {}

    def process_batch(self, batch, ctx, collector, input_index=0):
        out = []
        for k, v in self.state.items():
            out.append(v)
        collector.collect(out)
"""


def test_lr204_dict_attr_iteration_feeding_emit():
    assert "LR204" in ids_of(audit(LR204_BAD))


def test_lr204_sorted_iteration_is_clean():
    src = LR204_BAD.replace("self.state.items()", "sorted(self.state.items())")
    assert "LR204" not in ids_of(audit(src))


def test_lr204_comprehension_over_set_attr():
    src = """
from arroyo_tpu.operators.base import Operator

class C(Operator):
    def __init__(self, cfg):
        self.dirty = set()

    def process_batch(self, batch, ctx, collector, input_index=0):
        rows = [k for k in self.dirty]
        collector.collect(rows)
"""
    assert "LR204" in ids_of(audit(src))
    clean = src.replace("[k for k in self.dirty]",
                        "sorted(k for k in self.dirty)")
    assert "LR204" not in ids_of(audit(clean))


def test_lr204_annassign_attr_and_bare_iteration():
    # `self.buf: dict[...] = {}` is this repo's universal init style, and
    # bare `for t in self.buf:` iteration must be caught without an
    # .items()/.keys() call in the loop header
    src = """
from arroyo_tpu.operators.base import Operator

class C(Operator):
    def __init__(self, cfg):
        self.buf: dict[int, list] = {}

    def process_batch(self, batch, ctx, collector, input_index=0):
        for t in self.buf:
            collector.collect(self.buf[t])
"""
    assert "LR204" in ids_of(audit(src))
    assert "LR204" not in ids_of(audit(src.replace(
        "for t in self.buf:", "for t in sorted(self.buf):")))


def test_lr204_local_deterministic_dict_is_clean():
    src = """
from arroyo_tpu.operators.base import Operator

class C(Operator):
    def process_batch(self, batch, ctx, collector, input_index=0):
        cols = {}
        cols["a"] = 1
        out = [v for k, v in cols.items()]
        collector.collect(out)
"""
    assert "LR204" not in ids_of(audit(src))


def test_lr204_non_emitting_method_is_out_of_scope():
    src = LR204_BAD.replace("        collector.collect(out)\n", "")
    assert "LR204" not in ids_of(audit(src))


# ----------------------------------------------------------- determinism


def test_audit_output_deterministic_and_json_stable():
    a = audit(LR201_BAD + LR204_BAD.replace("class C", "class D"))
    b = audit(LR201_BAD + LR204_BAD.replace("class C", "class D"))
    assert [d.render() for d in a] == [d.render() for d in b]
    assert render_json(a) == render_json(b)
    assert all(set(d.to_dict()) == {"rule", "severity", "site", "message",
                                    "hint"} for d in a)


def test_same_named_classes_in_different_modules_both_audited():
    # review-round regression: the sweep keys classes by qualified name —
    # a name collision across modules must not silently drop one class
    from arroyo_tpu.analysis.state_audit import audit_modules
    from arroyo_tpu.analysis.repo_lint import _parse

    clean = """
from arroyo_tpu.operators.base import Operator

class Twin(Operator):
    def process_batch(self, batch, ctx, collector, input_index=0):
        pass
"""
    dirty = """
from arroyo_tpu.operators.base import Operator

class Twin(Operator):
    def __init__(self, cfg):
        self._cache = {}

    def process_batch(self, batch, ctx, collector, input_index=0):
        self._cache[1] = batch
"""
    diags, audits = audit_modules([
        _parse(clean, "operators/a.py"), _parse(dirty, "operators/b.py")])
    assert "LR201" in ids_of(diags)  # the SECOND Twin is still audited
    assert {"operators/a.py:Twin", "operators/b.py:Twin"} <= set(audits)


def test_repo_audit_clean():
    """The gate this PR's sweep earns: the whole package audits clean —
    every hot-path-mutated attribute is covered, flushed, or carries a
    justified waiver."""
    diags, audits = audit_package()
    assert diags == [], "\n".join(d.render() for d in diags)
    # and the sweep actually saw the fleet (not a silently-empty walk)
    names = {a.cls for a in audits.values()}
    assert {"TumblingAggregate", "SlidingAggregate", "UpdatingAggregate",
            "InstantJoin", "LookupJoin", "KafkaSink"} <= names


# ------------------------------------------------------------------ AR008


def _register_fixture_connectors():
    from arroyo_tpu.connectors import _SOURCES, register_source
    from arroyo_tpu.connectors.vec import VecSink
    from arroyo_tpu.operators.base import SourceOperator, TableSpec

    if "audit_dup_tables" not in _SOURCES:
        class DupTables(SourceOperator):
            def __init__(self, cfg):
                pass

            def tables(self):
                return [TableSpec("s", "global_keyed"),
                        TableSpec("s", "expiring_time_key")]

        register_source("audit_dup_tables")(DupTables)
    if "audit_ttl_mismatch" not in _SOURCES:
        class TtlMismatch(SourceOperator):
            def __init__(self, cfg):
                pass

            def tables(self):
                # retention hard-coded to 1s regardless of configured TTL
                return [TableSpec("x", "expiring_time_key",
                                  retention_micros=1_000_000)]

        register_source("audit_ttl_mismatch")(TtlMismatch)


def _source_graph(cfg: dict) -> Graph:
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, cfg, 1))
    g.add_node(Node("sink", OpName.SINK, {"connector": "blackhole"}, 1))
    g.add_edge("src", "sink", EdgeType.FORWARD, DUMMY)
    return g


def test_ar008_duplicate_table_specs_rejected():
    _register_fixture_connectors()
    diags = analyze_graph(_source_graph({"connector": "audit_dup_tables"}))
    d = [d for d in diags if d.rule_id == "AR008"]
    assert d and d[0].severity == Severity.ERROR and "'s'" in d[0].message


def test_ar008_ttl_mismatch_rejected_and_match_clean():
    _register_fixture_connectors()
    diags = analyze_graph(_source_graph(
        {"connector": "audit_ttl_mismatch", "ttl_micros": 3_600_000_000}))
    assert any(d.rule_id == "AR008" and "ttl" in d.message.lower()
               for d in diags)
    # matching TTL is clean
    diags = analyze_graph(_source_graph(
        {"connector": "audit_ttl_mismatch", "ttl_micros": 1_000_000}))
    assert "AR008" not in ids_of(diags)


def test_ar008_real_operators_consistent():
    """The production operators declare TTL-consistent specs: a join with
    a configured TTL plans clean."""
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE,
                    {"connector": "impulse", "message_count": 10}, 1))
    g.add_node(Node("j", OpName.JOIN_WITH_EXPIRATION,
                    {"left_names": [("a", "a")], "right_names": [("b", "b")],
                     "ttl_micros": 60_000_000}, 1))
    g.add_node(Node("sink", OpName.SINK, {"connector": "blackhole"}, 1))
    g.add_edge("src", "j", EdgeType.FORWARD, DUMMY)
    g.add_edge("j", "sink", EdgeType.FORWARD, DUMMY)
    assert "AR008" not in ids_of(analyze_graph(g))


# ----------------------------------------------- runtime cross-check


class _Collector:
    def __init__(self):
        self.batches: list[Batch] = []
        self.signals: list = []

    def collect(self, b):
        self.batches.append(b)

    def broadcast(self, s):
        self.signals.append(s)


def _ctx(storage_url: str, node_id: str = "op"):
    from arroyo_tpu.operators.base import OperatorContext

    ti = TaskInfo("xcheck", node_id, node_id, 0, 1)
    tm = TableManager(ti, storage_url)
    return OperatorContext(ti, None, tm), tm


_SKIP_TYPES = ("ThreadPoolExecutor",)


def _norm(v, depth=0):
    """Replay-equivalence normal form: numpy to python, containers sorted
    where identity-ordered, aggregator objects via their snapshot, lists
    of Batch as their concatenated row sequence."""
    assert depth < 12
    if type(v).__name__ in _SKIP_TYPES:
        return "<skipped>"
    if isinstance(v, Batch):
        return [sorted(r.items(), key=str) for r in v.to_pylist()]
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, dict):
        return sorted(((str(k), _norm(x, depth + 1)) for k, x in v.items()),
                      key=str)
    if isinstance(v, (set, frozenset)):
        return sorted(v, key=str)
    if isinstance(v, (list, tuple)):
        if v and all(isinstance(e, Batch) for e in v):
            return _norm(Batch.concat(list(v)), depth + 1)
        return [_norm(e, depth + 1) for e in v]
    if isinstance(v, (int, float, str, bytes, bool, type(None))):
        return v
    snap = getattr(v, "snapshot", None)
    if callable(snap):
        return _norm(snap(), depth + 1)
    if hasattr(v, "__dict__"):
        return _norm(vars(v), depth + 1)
    slots = getattr(type(v), "__slots__", None)
    if slots:
        return _norm({s: getattr(v, s, None) for s in slots}, depth + 1)
    return repr(v)


def _covered_attrs(op) -> list[str]:
    from arroyo_tpu.analysis import coverage_for_class

    audit_entry = coverage_for_class(type(op))
    assert audit_entry is not None, f"{type(op).__name__} not in the audit"
    return audit_entry.covered_attrs()


def _roundtrip_diff(make_op, drive, epoch: int, storage_url: str,
                    node_id: str) -> tuple[list[str], list[str]]:
    """Drive a fresh operator, checkpoint through a REAL TableManager,
    restore a second fresh operator from the files, and diff every
    audited-covered attribute. Returns (covered, mismatched)."""
    op = make_op()
    ctx, tm = _ctx(storage_url, node_id)
    col = _Collector()
    op.on_start(ctx)
    drive(op, ctx, col)
    op.handle_checkpoint(CheckpointBarrier(epoch=epoch), ctx, col)
    tm.checkpoint(epoch, watermark_micros=None)

    op2 = make_op()
    ctx2, tm2 = _ctx(storage_url, node_id)
    tm2.restore(epoch, op2.tables())
    op2.on_start(ctx2)

    covered = _covered_attrs(op)
    mism = []
    for a in covered:
        v1 = _norm(getattr(op, a, "<unset>"))
        v2 = _norm(getattr(op2, a, "<unset>"))
        if v1 != v2:
            mism.append(f"{type(op).__name__}.{a}: {v1!r} != {v2!r}")
    return covered, mism


def _kv_batch(ks, vs, ts):
    from arroyo_tpu.hashing import hash_columns

    k = np.asarray(ks, dtype=np.int64)
    return Batch({
        "k": k,
        "v": np.asarray(vs, dtype=np.int64),
        KEY_FIELD: hash_columns([k]),
        TIMESTAMP_FIELD: np.asarray(ts, dtype=np.int64),
    })


def test_runtime_cross_check_tumbling(_storage):
    """The smoke tumbling family's operator, checkpoint mid-stream (no
    window closed yet, so every covered attribute must round-trip
    bit-for-bit through the parquet state files)."""
    from arroyo_tpu.windows.tumbling import TumblingAggregate

    W = 1_000_000

    def make():
        return TumblingAggregate({
            "width_micros": W,
            "key_fields": ["k"],
            "aggregates": [("total", "sum", Col("v")), ("n", "count", None)],
            "input_dtype_of": lambda e: np.dtype(np.int64),
            "backend": "numpy",
        })

    def drive(op, ctx, col):
        op.process_batch(_kv_batch([1, 2, 1], [10, 20, 30],
                                   [100, 200, 300]), ctx, col)
        op.process_batch(_kv_batch([2, 3], [5, 7],
                                   [W + 100, W + 200]), ctx, col)

    covered, mism = _roundtrip_diff(make, drive, 1, _storage, "tumbling")
    assert not mism, "\n".join(mism)
    # the attrs at the heart of this PR's fix are in the covered set
    assert {"emitted_before_rel", "base_bin", "open_bins",
            "_agg"} <= set(covered)


def test_runtime_cross_check_detects_a_broken_restore(_storage):
    """The harness has teeth: an operator whose restore 'forgets' one
    covered attribute must produce a non-empty diff — this is exactly the
    disagreement between static verdict and runtime behavior the
    cross-check exists to catch."""
    from arroyo_tpu.windows.tumbling import TumblingAggregate

    class Amnesiac(TumblingAggregate):
        def on_start(self, ctx):
            super().on_start(ctx)
            self.open_bins = set()  # "forgets" restored state

    def make():
        return Amnesiac({
            "width_micros": 1_000_000,
            "key_fields": ["k"],
            "aggregates": [("total", "sum", Col("v"))],
            "input_dtype_of": lambda e: np.dtype(np.int64),
            "backend": "numpy",
        })

    def drive(op, ctx, col):
        op.process_batch(_kv_batch([1], [10], [100]), ctx, col)

    # the subclass inherits TumblingAggregate's audit via name match
    op = make()
    from arroyo_tpu.analysis import coverage_for_class

    base_audit = coverage_for_class(TumblingAggregate)
    assert "open_bins" in base_audit.covered_attrs()
    _, mism = _roundtrip_diff(make, drive, 1, _storage, "amnesiac")
    # the fabricated bug can only be visible in open_bins
    assert any("open_bins" in m for m in mism), mism


def test_runtime_cross_check_tumbling_late_boundary(_storage):
    """Behavioral leg of the LR201 fix: after a window closes and the
    epoch round-trips, the restored operator must drop a late row exactly
    like the original would — pre-fix, the restored operator re-opened the
    closed bin and re-emitted the window."""
    from arroyo_tpu.types import Signal, SignalKind
    from arroyo_tpu.windows.tumbling import TumblingAggregate

    W = 1_000_000

    def make():
        return TumblingAggregate({
            "width_micros": W,
            "key_fields": ["k"],
            "aggregates": [("total", "sum", Col("v"))],
            "input_dtype_of": lambda e: np.dtype(np.int64),
            "backend": "numpy",
        })

    op = make()
    ctx, tm = _ctx(_storage, "late")
    col = _Collector()
    op.on_start(ctx)
    op.process_batch(_kv_batch([1, 1], [10, 20], [100, W + 100]), ctx, col)
    # watermark past the first window closes and emits it
    out = op.handle_watermark(Watermark.event_time(W + 1), ctx, col)
    assert out is not None and len(col.batches) == 1
    op.handle_checkpoint(CheckpointBarrier(epoch=1), ctx, col)
    tm.checkpoint(1, watermark_micros=W + 1)
    assert op.emitted_before_rel is not None

    op2 = make()
    ctx2, tm2 = _ctx(_storage, "late")
    tm2.restore(1, op2.tables())
    op2.on_start(ctx2)
    # rel boundaries are anchored to each incarnation's base_bin (the
    # restored base is the snapshot's min bin): compare the ABSOLUTE bin
    assert op2.emitted_before_rel is not None
    assert (op2.emitted_before_rel + op2.base_bin
            == op.emitted_before_rel + op.base_bin)

    # a late row behind the emitted window: BOTH incarnations must drop it
    late = _kv_batch([1], [99], [200])
    col_a, col_b = _Collector(), _Collector()
    op.process_batch(late, ctx, col_a)
    op2.process_batch(late, ctx2, col_b)
    assert op.late_rows == 1 and op2.late_rows == 1
    op.on_close(ctx, col_a)
    op2.on_close(ctx2, col_b)
    assert [_norm(b) for b in col_a.batches] == [_norm(b) for b in col_b.batches]


def test_runtime_cross_check_tumbling_empty_snapshot_keeps_boundary(_storage):
    """Review-round regression: when EVERY window has closed by the
    barrier, the partial snapshot is empty — the late-data boundary must
    survive anyway (it rides the 'e' global table, not a column on the
    't' batch), and the restored operator must still drop late rows."""
    from arroyo_tpu.windows.tumbling import TumblingAggregate

    W = 1_000_000

    def make():
        return TumblingAggregate({
            "width_micros": W,
            "key_fields": ["k"],
            "aggregates": [("total", "sum", Col("v"))],
            "input_dtype_of": lambda e: np.dtype(np.int64),
            "backend": "numpy",
        })

    op = make()
    ctx, tm = _ctx(_storage, "empty")
    col = _Collector()
    op.on_start(ctx)
    op.process_batch(_kv_batch([1], [10], [100]), ctx, col)
    # watermark closes the ONLY window: partial state is now empty
    op.handle_watermark(Watermark.event_time(2 * W), ctx, col)
    assert len(col.batches) == 1 and not op.open_bins
    op.handle_checkpoint(CheckpointBarrier(epoch=1), ctx, col)
    tm.checkpoint(1, watermark_micros=2 * W)

    op2 = make()
    ctx2, tm2 = _ctx(_storage, "empty")
    tm2.restore(1, op2.tables())
    op2.on_start(ctx2)
    assert op2.emitted_before_rel is not None
    col2 = _Collector()
    op2.process_batch(_kv_batch([1], [99], [200]), ctx2, col2)  # late row
    assert op2.late_rows == 1
    op2.on_close(ctx2, col2)
    assert col2.batches == [], "restored op re-emitted an already-closed window"


def test_runtime_cross_check_updating_aggregate(_storage):
    from arroyo_tpu.operators.updating_aggregate import UpdatingAggregate

    def make():
        return UpdatingAggregate({
            "key_fields": ["k"],
            "aggregates": [("total", "sum", Col("v")), ("n", "count", None)],
            "input_dtype_of": lambda e: np.dtype(np.int64),
            "ttl_micros": 3_600_000_000,
            "backend": "numpy",
        })

    def drive(op, ctx, col):
        op.process_batch(_kv_batch([1, 2, 1], [10, 20, 30],
                                   [100, 200, 9_000_000]), ctx, col)
        op.handle_tick(ctx, col)  # flush -> `emitted` mirrors downstream
        op.process_batch(_kv_batch([2], [5], [9_500_000]), ctx, col)

    covered, mism = _roundtrip_diff(make, drive, 1, _storage, "upd")
    assert not mism, "\n".join(mism)
    assert {"state", "key_values", "max_event_time"} <= set(covered)


def test_runtime_cross_check_instant_join(_storage):
    from arroyo_tpu.operators.joins import InstantJoin

    def make():
        return InstantJoin({
            "join_type": "inner",
            "left_names": [("lv", "v")],
            "right_names": [("rv", "v")],
            "backend": "numpy",
        })

    class Ctx2:
        pass

    def drive(op, ctx, col):
        # edge_of_input maps flat input index -> side
        ctx._in_edge_of_input = lambda i: (i, 0)
        op.process_batch(_kv_batch([1, 2], [10, 20], [100, 100]),
                         ctx, col, input_index=0)
        op.process_batch(_kv_batch([1], [7], [100]), ctx, col, input_index=1)

    covered, mism = _roundtrip_diff(make, drive, 1, _storage, "ij")
    assert not mism, "\n".join(mism)
    assert "buf" in covered and "emitted_before" in covered


def test_runtime_cross_check_lookup_join_cache(_storage):
    """The table the audit found declared-but-unwired (LR203): the lookup
    cache now checkpoints into 'c' and restores, so replayed batches
    resolve from the same cache state the original run had."""
    from arroyo_tpu.operators.joins import LookupJoin

    class Src:
        def __init__(self):
            self.calls = 0

        def lookup(self, keys):
            self.calls += 1
            return {k: {"name": f"row-{int(k)}"} for k in keys}

    src = Src()

    def make():
        return LookupJoin({
            "connector": src,
            "key_exprs": [Col("k")],
            "right_names": [("name", "name")],
            "join_type": "left",
        })

    def drive(op, ctx, col):
        op.process_batch(_kv_batch([1, 2], [0, 0], [100, 100]), ctx, col)

    covered, mism = _roundtrip_diff(make, drive, 1, _storage, "lj")
    assert not mism, "\n".join(mism)
    assert "cache" in covered
    # and the restored cache actually serves: replaying the same batch
    # must not re-ask the external source
    op2 = make()
    ctx2, tm2 = _ctx(_storage, "lj")
    tm2.restore(1, op2.tables())
    op2.on_start(ctx2)
    calls_before = src.calls
    col = _Collector()
    op2.process_batch(_kv_batch([1, 2], [0, 0], [100, 100]), ctx2, col)
    op2.handle_checkpoint(CheckpointBarrier(epoch=2), ctx2, col)
    assert src.calls == calls_before, "restored cache did not serve replay"
    assert len(col.batches) == 1 and "name" in col.batches[0].columns


def test_runtime_cross_check_watermark_generator(_storage):
    from arroyo_tpu.operators.builtin import WatermarkGenerator

    def make():
        return WatermarkGenerator({"expr": Col(TIMESTAMP_FIELD)})

    def drive(op, ctx, col):
        op.process_batch(_kv_batch([1], [1], [5_000]), ctx, col)

    covered, mism = _roundtrip_diff(make, drive, 1, _storage, "wm")
    assert not mism, "\n".join(mism)
    assert {"max_watermark", "last_emitted"} <= set(covered)
