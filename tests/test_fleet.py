"""Multi-tenant fleet (controller/fleet.py): slot-aware admission, fair
per-tenant queueing with quotas, per-job supervision isolation, and fleet
elasticity — ROADMAP item 5.

The queue/quota state machine is driven with a FAKE clock (no wall-time
sleeps for backoff/cooldown); the chaos e2e runs ~10 concurrent smoke
jobs across two tenants on a synthetic pool smaller than total demand and
asserts byte-exact goldens for every one of them through a worker crash,
a live rescale, and an injected melting job.
"""

from __future__ import annotations

import glob
import json
import os
import time

import pytest

from arroyo_tpu.controller import ControllerServer, Database
from arroyo_tpu.controller.fleet import FleetManager, demand_slots
from arroyo_tpu.controller.scheduler import EmbeddedScheduler
from arroyo_tpu.controller.states import JobState

SMOKE = os.path.join(os.path.dirname(__file__), "smoke")


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def _sql(tmp_path, idx=0, name="grouped_aggregates"):
    with open(os.path.join(SMOKE, "queries", f"{name}.sql")) as f:
        sql = f.read()
    out = str(tmp_path / f"out{idx}.json")
    return sql.replace("$input_dir", os.path.join(SMOKE, "inputs")).replace(
        "$output_path", out), out


def _assert_golden(out, name="grouped_aggregates"):
    got = []
    for p in sorted(glob.glob(out) + glob.glob(out + ".*")):
        with open(p) as f:
            got.extend(json.loads(l) for l in f if l.strip())
    with open(os.path.join(SMOKE, "golden", f"{name}.json")) as f:
        want = [json.loads(l) for l in f if l.strip()]
    key = lambda r: json.dumps(r, sort_keys=True)  # noqa: E731
    assert sorted(map(key, got)) == sorted(map(key, want)), out


# ---------------------------------------------------- fake-clock unit layer


def test_demand_slots():
    assert demand_slots(1, 1) == 1
    assert demand_slots(2, 1) == 2  # at least one slot per worker process
    assert demand_slots(1, 4) == 4  # one slot per parallel lane
    assert demand_slots(0, 0) == 1


def test_unlimited_pool_is_pass_through(_storage):
    fm = FleetManager(clock=FakeClock())
    assert fm.admit("j1", "a", 3)[0] == "admitted"
    assert fm.pool_slots() is None
    assert fm.stats()["slots_free"] is None


def test_drr_admission_order_alternates_tenants(_storage):
    """Pool of 2; tenant A queues three 1-slot jobs, tenant B two. As
    capacity frees one slot at a time, grants alternate A/B (deficit
    round-robin) — FIFO within each tenant."""
    from arroyo_tpu import config as cfg

    cfg.update({"fleet.slots": 2})
    fm = FleetManager(clock=FakeClock())
    assert fm.admit("a1", "A", 1)[0] == "admitted"
    assert fm.admit("a2", "A", 1)[0] == "admitted"
    for j in ("a3", "a4", "a5"):
        assert fm.admit(j, "A", 1)[0] == "queued"
    for j in ("b1", "b2"):
        assert fm.admit(j, "B", 1)[0] == "queued"
    # queue positions interleave by tenant (round-robin view)
    assert fm.queue_position("a3") == 1 or fm.queue_position("b1") == 1
    order = []
    for done in ("a1", "a2", "b1", "a3", "b2"):
        fm.release(done)
        fm.tick(None)
        order += [j for j in ("a3", "a4", "a5", "b1", "b2")
                  if fm.should_admit(j)]
    assert order == ["b1", "a3", "b2", "a4", "a5"], order


def test_big_job_not_starved_capacity_reservation(_storage):
    """A 3-slot job whose tenant is next in rotation RESERVES freed
    capacity: a stream of 1-slot jobs from another tenant cannot be
    granted around it once it is credit-eligible."""
    from arroyo_tpu import config as cfg

    cfg.update({"fleet.slots": 3})
    fm = FleetManager(clock=FakeClock())
    for j in ("s1", "s2", "s3"):
        assert fm.admit(j, "small", 1)[0] == "admitted"
    assert fm.admit("big", "big-tenant", 3)[0] == "queued"
    for j in ("s4", "s5"):
        assert fm.admit(j, "small", 1)[0] == "queued"
    # free one slot at a time: nothing admits until all 3 are free — the
    # big head holds the reservation
    fm.release("s1")
    fm.tick(None)
    assert not fm.should_admit("s4") and not fm.should_admit("big")
    fm.release("s2")
    fm.tick(None)
    assert not fm.should_admit("s4") and not fm.should_admit("big")
    fm.release("s3")
    fm.tick(None)
    assert fm.should_admit("big")
    # with big placed (3/3 used), smalls wait their turn
    assert not fm.should_admit("s4")


def test_unfittable_job_does_not_starve_other_tenants(_storage):
    """A queued job whose demand exceeds what the pool could EVER offer
    (> pool, no elasticity) stays Queued but must NOT hold the admission
    pass hostage: other tenants' jobs keep admitting around it."""
    from arroyo_tpu import config as cfg

    cfg.update({"fleet.slots": 2})
    fm = FleetManager(clock=FakeClock())
    assert fm.admit("whale", "B", 5)[0] == "queued"  # can never fit
    assert fm.admit("a1", "A", 1)[0] == "admitted"
    fm.release("a1")
    # rotation cursor now sits so B's unfittable head is visited first —
    # the shape that froze the whole fleet before the fix
    assert fm.admit("a2", "A", 1)[0] == "admitted"
    assert fm.admit("a3", "A", 1)[0] == "admitted"
    assert fm.queue_position("whale") == 1  # still visibly queued
    # with elasticity up to 8 the whale becomes achievable: now it DOES
    # reserve freed capacity instead of being skipped
    cfg.update({"fleet.autoscale.enabled": True,
                "fleet.autoscale.max-slots": 8})
    fm.release("a2")
    fm.tick(None)
    assert fm.admit("a4", "A", 1)[0] == "queued", (
        "an achievable big head must reserve freed capacity again")


def test_tenant_label_escaped_in_prometheus(_storage):
    from arroyo_tpu import config as cfg
    from arroyo_tpu.metrics import registry as metrics_registry

    cfg.update({"fleet.slots": 1})
    fm = FleetManager(clock=FakeClock())
    assert fm.admit("j1", "t", 1)[0] == "admitted"
    assert fm.admit("j2", 'evil"tenant\nx', 1)[0] == "queued"
    metrics_registry.set_fleet_stats(fm.stats())
    try:
        text = metrics_registry.prometheus_text()
        assert 'tenant="evil\\"tenant\\nx"' in text
    finally:
        metrics_registry.set_fleet_stats(None)


def test_quota_rejection_vs_queueing(_storage):
    """Demand beyond the tenant's max-slots quota REJECTS (could never
    run); merely exceeding current headroom QUEUES and re-admits when a
    peer finishes. max-jobs caps concurrent jobs the same way."""
    from arroyo_tpu import config as cfg

    cfg.update({"fleet.quota.max-slots": 2})
    fm = FleetManager(clock=FakeClock())
    verdict, reason = fm.admit("big", "t", 3)
    assert verdict == "rejected" and "could never run" in reason
    assert fm.admit("j1", "t", 1)[0] == "admitted"
    assert fm.admit("j2", "t", 2)[0] == "queued"  # 1 + 2 > 2: waits
    fm.release("j1")
    fm.tick(None)
    assert fm.should_admit("j2")
    # max-jobs: a second concurrent job queues even with slot headroom
    cfg.update({"fleet.quota.max-slots": 0, "fleet.quota.max-jobs": 1})
    fm2 = FleetManager(clock=FakeClock())
    assert fm2.admit("x1", "t", 1)[0] == "admitted"
    assert fm2.admit("x2", "t", 1)[0] == "queued"
    fm2.release("x1")
    fm2.tick(None)
    assert fm2.should_admit("x2")


def test_per_tenant_quota_override(_storage):
    from arroyo_tpu import config as cfg

    cfg.update({"fleet.quota.max-slots": 1,
                "fleet.quota.tenants.gold.max-slots": 4})
    fm = FleetManager(clock=FakeClock())
    assert fm.admit("g", "gold", 3)[0] == "admitted"
    assert fm.admit("b", "bronze", 3)[0] == "rejected"


def test_requeue_backoff_deterministic_doubling(_storage):
    """Repeated placement 409s: the job re-queues at the head of its
    tenant queue but is ineligible for base * 2^(k-1) seconds — exact and
    jitter-free, driven by a fake clock."""
    from arroyo_tpu import config as cfg

    cfg.update({"fleet.slots": 4})
    clk = FakeClock()
    fm = FleetManager(clock=clk)
    assert fm.admit("j", "t", 1)[0] == "admitted"
    fm.requeue("j", "t", 1, backoff=True)
    assert fm.backoff_remaining("j") == pytest.approx(0.5)
    fm.tick(None)
    assert not fm.should_admit("j"), "granted during backoff"
    clk.advance(0.6)
    fm.tick(None)
    assert fm.should_admit("j")
    fm.requeue("j", "t", 1, backoff=True)
    assert fm.backoff_remaining("j") == pytest.approx(1.0)
    fm.requeue("j", "t", 1, backoff=True)
    assert fm.backoff_remaining("j") == pytest.approx(2.0)
    # a landed placement resets the streak
    fm.clear_backoff("j")
    fm.requeue("j", "t", 1, backoff=True)
    assert fm.backoff_remaining("j") == pytest.approx(0.5)


def test_backoff_head_does_not_block_other_tenants(_storage):
    from arroyo_tpu import config as cfg

    cfg.update({"fleet.slots": 1})
    clk = FakeClock()
    fm = FleetManager(clock=clk)
    assert fm.admit("a1", "A", 1)[0] == "admitted"
    fm.requeue("a1", "A", 1, backoff=True)  # head of A, in backoff
    assert fm.admit("b1", "B", 1)[0] == "admitted", (
        "a backoff-gated head must not hold capacity hostage")


def test_preemption_marks_newest_of_over_quota_tenant(_storage):
    from arroyo_tpu import config as cfg

    fm = FleetManager(clock=FakeClock())
    assert fm.admit("old", "t", 1)[0] == "admitted"
    assert fm.admit("new", "t", 1)[0] == "admitted"
    cfg.update({"fleet.quota.max-slots": 1})  # quota lowered below usage
    fm.tick(None)
    assert fm.take_preemption("new")
    assert not fm.take_preemption("old")
    # marked-and-taken: not re-marked while the drain is in flight
    fm.tick(None)
    assert not fm.take_preemption("new")
    # the drain landed -> requeue; with usage back within quota no
    # further preemption fires
    fm.requeue("new", "t", 1)
    fm.tick(None)
    assert not fm.take_preemption("old")


def test_fleet_autoscaler_grows_and_shrinks_synthetic_pool(_storage):
    """Capacity-blocked queue demand is fleet pressure: after up-ticks
    the pool grows toward demand through the scheduler's provision hook
    (synthetic pools apply it directly); sustained surplus shrinks it
    back toward usage, floored at the configured base."""
    from arroyo_tpu import config as cfg

    cfg.update({"fleet.slots": 2, "fleet.autoscale.enabled": True,
                "fleet.autoscale.max-slots": 8,
                "fleet.autoscale.up-ticks": 2,
                "fleet.autoscale.down-ticks": 3,
                "fleet.autoscale.cooldown-s": 5.0})
    clk = FakeClock()
    fm = FleetManager(scheduler=EmbeddedScheduler(), clock=clk)
    assert fm.admit("j1", "t", 1)[0] == "admitted"
    assert fm.admit("j2", "t", 1)[0] == "admitted"
    assert fm.admit("j3", "t", 2)[0] == "queued"
    fm.tick(None)  # pressure tick 1
    assert fm.pool_slots() == 2
    fm.tick(None)  # pressure tick 2 -> resize
    assert fm.pool_slots() == 4, fm.stats()
    fm.tick(None)  # the grown pool admits the queued job
    assert fm.should_admit("j3")
    assert fm.stats()["target_workers"] == 4
    # shrink: drain usage, wait out cooldown, three surplus ticks
    fm.release("j1")
    fm.release("j2")
    fm.release("j3")
    clk.advance(6.0)
    for _ in range(3):
        fm.tick(None)
    assert fm.pool_slots() == 2, "pool must shrink back to the base"


def test_fleet_place_fault_force_and_drop(_storage):
    """Chaos site fleet_place: drop suppresses a placement decision for
    the pass; force grants regardless of capacity (the ledger absorbs the
    oversubscription as pressure)."""
    from arroyo_tpu import config as cfg, faults

    cfg.update({"fleet.slots": 1})
    fm = FleetManager(clock=FakeClock())
    assert fm.admit("j1", "t", 1)[0] == "admitted"
    faults.install("fleet_place:force=1@key=j2", seed=0)
    try:
        assert fm.admit("j2", "t", 1)[0] == "admitted", (
            "force must grant past a full pool")
        assert fm.stats()["slots_used"] == 2  # oversubscribed, visible
        faults.install("fleet_place:drop@key=j3", seed=0)
        fm.release("j1")
        fm.release("j2")
        assert fm.admit("j3", "t", 1)[0] == "queued", (
            "drop must suppress the grant")
    finally:
        faults.clear()
    fm.tick(None)  # plan cleared: the next pass grants normally
    assert fm.should_admit("j3")


def test_tick_budget_deprioritizes_but_never_starves(_storage):
    """ControllerServer.tick: a job whose supervision step overruns
    fleet.tick-budget-ms emits JOB_TICK_OVERRUN and is deprioritized —
    neighbors step every tick, the offender still steps regularly."""
    from arroyo_tpu import config as cfg

    cfg.update({"fleet.tick-budget-ms": 40, "fleet.tick-penalty-max": 2})

    class StubJC:
        def __init__(self, slow_ms):
            self.state = JobState.RUNNING
            self.slow_ms = slow_ms
            self.steps = 0
            self.events = []

        def is_terminal(self):
            return False

        def step(self):
            self.steps += 1
            time.sleep(self.slow_ms / 1000.0)

        def _event(self, level, code, message, **kw):
            self.events.append(code)

    db = Database()
    ctl = ControllerServer(db, EmbeddedScheduler())
    slow, fast = StubJC(90), StubJC(0)
    ctl.jobs = {"slow": slow, "fast": fast}
    for _ in range(12):
        ctl.tick()
    assert "JOB_TICK_OVERRUN" in slow.events
    assert not fast.events
    assert fast.steps == 12, "compliant neighbors step every tick"
    # deprioritized, not starved: with penalty cap 2 the offender steps
    # at least every third tick
    assert 3 <= slow.steps < 12, slow.steps
    # penalty decays once the job behaves again
    slow.slow_ms = 0
    for _ in range(8):
        ctl.tick()
    assert ctl._tick_penalty.get("slow", 0) == 0


def test_fleet_target_gauge_tracks_demand_on_external_pool(_storage):
    """Externally sized pool (provision hook returns None — the node/k8s
    case): a standing target must not re-arm the cooldown every tick; it
    keeps FOLLOWING demand up and down so the node-pool knob stays
    live."""
    from arroyo_tpu import config as cfg

    cfg.update({"fleet.slots": 4, "fleet.autoscale.enabled": True,
                "fleet.autoscale.max-slots": 64,
                "fleet.autoscale.up-ticks": 2,
                "fleet.autoscale.down-ticks": 2,
                "fleet.autoscale.cooldown-s": 5.0})
    clk = FakeClock()
    fm = FleetManager(scheduler=None, clock=clk)  # no provision hook
    for i in range(4):
        assert fm.admit(f"j{i}", "t", 1)[0] == "admitted"
    assert fm.admit("q1", "t", 4)[0] == "queued"
    fm.tick(None)
    fm.tick(None)
    assert fm.pool_slots() == 4, "external pool must not resize itself"
    assert fm.stats()["target_workers"] == 8
    # demand grows: after cooldown the target must follow (the first-cut
    # bug re-armed the cooldown every tick and froze the gauge forever)
    assert fm.admit("q2", "t", 4)[0] == "queued"
    clk.advance(6.0)
    fm.tick(None)
    fm.tick(None)
    assert fm.stats()["target_workers"] == 12, fm.stats()
    # demand drains: the target follows back down
    for j in ("j0", "j1", "j2", "j3", "q1", "q2"):
        fm.release(j)
    clk.advance(6.0)
    fm.tick(None)
    fm.tick(None)
    assert fm.stats()["target_workers"] == 4


def test_restore_queued_preserves_persisted_fifo_order(_storage):
    """Controller restart: adopted Queued jobs re-enter at their
    PERSISTED positions — whichever JobController ticks first — instead
    of head-inserting in adoption order (which reversed FIFO)."""
    from arroyo_tpu import config as cfg

    cfg.update({"fleet.slots": 1})
    fm = FleetManager(clock=FakeClock())
    assert fm.admit("run", "t", 1)[0] == "admitted"
    # adoption order B-then-A (the reversing shape); positions say A=1
    fm.restore_queued("B", "t", 1, position=2)
    fm.restore_queued("A", "t", 1, position=1)
    fm.restore_queued("C", "t", 1, position=None)  # fresh: goes last
    assert [e.job_id for e in fm.queue_order()] == ["A", "B", "C"]
    fm.release("run")
    fm.tick(None)
    assert fm.should_admit("A") and not fm.should_admit("B")


def test_manual_restart_reenters_admission(tmp_path, _storage):
    """A restart of a TERMINAL job released its slots: the fresh
    JobController must NOT adopt them in __init__ — it re-enters
    admission, queueing behind a full pool instead of oversubscribing."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.controller.controller import JobController

    sql, _out = _sql(tmp_path, 0)
    db = Database()
    cfg.update({"fleet.slots": 1})
    fm = FleetManager(clock=FakeClock())
    assert fm.admit("other", "t", 1)[0] == "admitted"  # pool full
    pid = db.create_pipeline("r", sql, 1)
    jid = db.create_job(pid, tenant="t")
    db.update_job(jid, state="Restarting")
    jc = JobController(db, jid, EmbeddedScheduler(), fleet=fm)
    assert not fm.holds(jid), (
        "__init__ must not adopt slots for a Restarting job")
    jc.step()  # the restart path runs admission -> Queued (pool is full)
    assert jc.state == JobState.QUEUED
    assert fm.queue_position(jid) == 1
    # the peer finishing frees the slot and the restart proceeds
    fm.release("other")
    fm.tick(None)
    jc.step()
    assert jc.state == JobState.SCHEDULING
    jc._kill_all()


# ------------------------------------------------------- controller layer


def test_queue_admit_finish_and_api_surfaces(tmp_path, _storage):
    """Pool of 1, two jobs: the second lands in QUEUED (JOB_QUEUED event,
    API queue position, fleet snapshot, nonzero queue-depth gauge, `top`
    header), admits automatically when the first finishes, and both reach
    byte-exact goldens."""
    import urllib.request

    from arroyo_tpu import config as cfg
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.metrics import registry as metrics_registry
    from arroyo_tpu.obs import topview

    sql1, out1 = _sql(tmp_path, 0)
    sql2, out2 = _sql(tmp_path, 1)
    db = Database()
    cfg.update({"fleet.slots": 1, "checkpoint.interval-ms": 200,
                # j1 must outlive the whole block of API/gauge/top
                # assertions against the still-queued j2
                "testing.source-read-delay-micros": 12_000})
    api = ApiServer(db, port=0).start()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()

    def get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{api.port}{path}") as r:
            return json.loads(r.read())

    try:
        p1 = db.create_pipeline("one", sql1, 1)
        j1 = db.create_job(p1, tenant="acme")
        ctl.wait_for_state(j1, "Running", timeout=60)
        p2 = db.create_pipeline("two", sql2, 1)
        j2 = db.create_job(p2, tenant="acme")
        ctl.wait_for_state(j2, "Queued", timeout=60)
        # API: job row carries tenant + queue position; /fleet shows the
        # pool, the queue, and per-tenant usage. The fleet snapshot
        # persists on the NEXT supervision tick after the state flip, so
        # poll briefly.
        deadline = time.monotonic() + 10
        row = get(f"/api/v1/jobs/{j2}")
        while "queue_position" not in row and time.monotonic() < deadline:
            time.sleep(0.05)
            row = get(f"/api/v1/jobs/{j2}")
        assert row["tenant"] == "acme"
        assert row["queue_position"] == 1
        fleet = get("/api/v1/fleet")
        assert fleet["pool_slots"] == 1 and fleet["slots_free"] == 0
        assert fleet["queue_depth"] == {"acme": 1}
        assert fleet["queue"][0]["job_id"] == j2
        assert fleet["tenants"]["acme"]["jobs_running"] == 1
        # gauge: queue depth is visible while the job waits
        text = metrics_registry.prometheus_text()
        assert 'arroyo_fleet_queue_depth{tenant="acme"} 1' in text
        assert 'arroyo_fleet_slots{state="used"} 1' in text
        # `top` header for a queued job
        frame = topview.render(row, None)
        assert "state=Queued" in frame and "queue_pos=1" in frame \
            and "tenant=acme" in frame
        # events: the admission decision is in the job's feed
        evs = [e["code"] for e in db.list_events(j2)]
        assert "JOB_QUEUED" in evs
        # capacity frees -> automatic admission -> both finish
        ctl.wait_for_state(j1, "Finished", timeout=120)
        ctl.wait_for_state(j2, "Finished", timeout=120)
        evs = [e["code"] for e in db.list_events(j2)]
        assert "JOB_ADMITTED" in evs
        _assert_golden(out1)
        _assert_golden(out2)
    finally:
        cfg.update({"checkpoint.interval-ms": 10_000,
                    "testing.source-read-delay-micros": 0})
        ctl.stop()
        api.stop()


def test_never_placeable_job_stays_queued_and_cancel_path(tmp_path,
                                                          _storage):
    """A job whose demand exceeds the pool (no elasticity) stays QUEUED —
    not Failed — with the queue depth visible; a stop request cancels it
    straight to Stopped (the QUEUED -> Stopped path)."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.metrics import registry as metrics_registry

    sql, _out = _sql(tmp_path, 0)
    db = Database()
    cfg.update({"fleet.slots": 1})
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("big", sql, 2)  # demand 2 > pool 1
        jid = db.create_job(pid, tenant="t")
        ctl.wait_for_state(jid, "Queued", timeout=60)
        time.sleep(1.0)  # several supervision ticks: it must NOT fail
        job = db.get_job(jid)
        assert job["state"] == "Queued", job["state"]
        text = metrics_registry.prometheus_text()
        assert 'arroyo_fleet_queue_depth{tenant="t"} 1' in text
        db.update_job(jid, desired_stop="immediate")
        assert ctl.wait_for_state(jid, "Stopped", timeout=30) == "Stopped"
        # the queue entry is gone with it
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            fs = db.get_fleet_state() or {}
            if not fs.get("queue"):
                break
            time.sleep(0.05)
        assert not (db.get_fleet_state() or {}).get("queue")
    finally:
        ctl.stop()


def test_structural_quota_rejection_fails_job(tmp_path, _storage):
    from arroyo_tpu import config as cfg

    sql, _out = _sql(tmp_path, 0)
    db = Database()
    cfg.update({"fleet.quota.max-slots": 1})
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("toobig", sql, 2)  # demand 2 > quota 1
        jid = db.create_job(pid, tenant="t")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if db.get_job(jid)["state"] == "Failed":
                break
            time.sleep(0.05)
        job = db.get_job(jid)
        assert job["state"] == "Failed"
        assert "could never run" in (job["failure_message"] or "")
        # the event feed flushes on the tick after the state write
        deadline = time.monotonic() + 10
        codes: list = []
        while time.monotonic() < deadline:
            codes = [e["code"] for e in db.list_events(jid)]
            if "JOB_REJECTED" in codes:
                break
            time.sleep(0.05)
        assert "JOB_REJECTED" in codes, codes
    finally:
        ctl.stop()


def test_placement_409_requeues_without_restart_budget(tmp_path, _storage):
    """The admission chaos site models a node 409 at placement: the job
    re-queues with deterministic backoff (WARN JOB_QUEUED), never routes
    through _on_worker_failed, burns zero restart-budget tokens, and
    still finishes byte-exact."""
    from arroyo_tpu import config as cfg, faults

    sql, out = _sql(tmp_path, 0)
    db = Database()
    cfg.update({"fleet.slots": 2})
    faults.install("admission:fail_n=2", seed=3)
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("bounce", sql, 1)
        jid = db.create_job(pid, tenant="t")
        assert ctl.wait_for_state(jid, "Finished", timeout=120) == "Finished"
        job = db.get_job(jid)
        assert int(job["restarts"] or 0) == 0, (
            "a 409 must not burn a restart-budget token")
        evs = db.list_events(jid)
        bounced = [e for e in evs if e["code"] == "JOB_QUEUED"
                   and e["level"] == "WARN"]
        assert len(bounced) == 2, [(e["level"], e["code"]) for e in evs]
        assert all(e["data"].get("backoff_s", 0) > 0 for e in bounced)
        assert "WORKER_LOST" not in [e["code"] for e in evs]
        _assert_golden(out)
    finally:
        faults.clear()
        ctl.stop()


def test_quota_change_preempts_drains_and_requeues(tmp_path, _storage):
    """Lowering a tenant's quota below usage preempts its NEWEST admitted
    job: JOB_PREEMPTED, drain behind a final checkpoint, JOB_QUEUED
    (reason preempted), automatic re-admission when the peer finishes —
    and both jobs' goldens stay byte-exact (the preempted one restores
    from its drain checkpoint)."""
    from arroyo_tpu import config as cfg

    sql1, out1 = _sql(tmp_path, 0)
    sql2, out2 = _sql(tmp_path, 1)
    db = Database()
    cfg.update({"fleet.slots": 4, "checkpoint.interval-ms": 150,
                "testing.source-read-delay-micros": 5000})
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        p1 = db.create_pipeline("one", sql1, 1)
        j1 = db.create_job(p1, tenant="X")
        p2 = db.create_pipeline("two", sql2, 1)
        j2 = db.create_job(p2, tenant="X")
        ctl.wait_for_state(j1, "Running", timeout=60)
        ctl.wait_for_state(j2, "Running", timeout=60)
        time.sleep(0.5)  # let checkpoints land on both
        cfg.update({"fleet.quota.max-slots": 1})
        # the newest ADMISSION preempts (admission order follows the
        # controller's adoption order, not job creation order) — find it
        # by its event
        deadline = time.monotonic() + 30
        victim = None
        while time.monotonic() < deadline and victim is None:
            for j in (j1, j2):
                if "JOB_PREEMPTED" in [e["code"] for e in db.list_events(j)]:
                    victim = j
                    break
            time.sleep(0.05)
        assert victim is not None, "no job was preempted"
        ctl.wait_for_state(victim, "Queued", "Finished", timeout=60)
        # peer finishes -> usage fits the quota -> victim re-admits
        for j in (j1, j2):
            assert ctl.wait_for_state(j, "Finished",
                                      timeout=120) == "Finished"
        codes = [e["code"] for e in db.list_events(victim)]
        assert "JOB_PREEMPTED" in codes and "JOB_ADMITTED" in codes
        q = [e for e in db.list_events(victim) if e["code"] == "JOB_QUEUED"]
        assert any(e["data"].get("reason") == "preempted" for e in q), q
        assert int(db.get_job(victim)["restarts"] or 0) == 0
        _assert_golden(out1)
        _assert_golden(out2)
    finally:
        cfg.update({"fleet.quota.max-slots": 0,
                    "checkpoint.interval-ms": 10_000,
                    "testing.source-read-delay-micros": 0})
        ctl.stop()


def test_autoscale_blocked_by_fleet_capacity_then_grows(tmp_path, _storage):
    """A per-job autoscale scale-up the pool cannot place is skipped with
    the hysteresis re-armed (AUTOSCALE_DECISION blocked_by fleet-capacity)
    and becomes fleet pressure; with fleet elasticity on, the pool grows
    and the re-armed decision actuates."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.controller.autoscaler import Autoscaler

    cfg.update({"fleet.slots": 1, "autoscaler.enabled": True,
                "autoscaler.up-ticks": 1, "autoscaler.cooldown-s": 0.0})
    clk = FakeClock()
    fm = FleetManager(scheduler=EmbeddedScheduler(), clock=clk)
    assert fm.admit("j", "t", 1)[0] == "admitted"
    events = []
    a = Autoscaler("j", emit=lambda lvl, code, msg, **kw:
                   events.append((code, kw.get("data") or {})), clock=clk)
    pressured = {"op": {"backpressure": 0.95, "per_subtask": {}}}
    target = a.evaluate(pressured, running=True, parallelism=1)
    assert target == 2
    assert not fm.try_grow("j", demand_slots(1, target))
    a.on_capacity_blocked(1, target)
    blocked = [d for c, d in events if c == "AUTOSCALE_DECISION"
               and d.get("blocked_by") == "fleet-capacity"]
    assert blocked and blocked[0]["to"] == 2
    # hysteresis re-armed: the next pressured tick re-decides immediately
    assert a.evaluate(pressured, running=True, parallelism=1) == 2
    # the fleet grows (elasticity) and the reservation then succeeds
    cfg.update({"fleet.autoscale.enabled": True,
                "fleet.autoscale.up-ticks": 1,
                "fleet.autoscale.max-slots": 8})
    fm.tick(None)
    assert fm.pool_slots() >= 2, fm.stats()
    assert fm.try_grow("j", 2)
    # dedup: repeating the same block emits no second event
    n = len(blocked)
    a.on_capacity_blocked(1, 2)
    blocked2 = [d for c, d in events if c == "AUTOSCALE_DECISION"
                and d.get("blocked_by") == "fleet-capacity"]
    assert len(blocked2) == n


# ------------------------------------------------------------- chaos e2e


@pytest.mark.chaos
def test_fleet_chaos_ten_jobs_two_tenants_shared_pool(tmp_path, _storage):
    """The ROADMAP item 5 acceptance run: ~10 concurrent smoke jobs from
    two tenants on a 4-slot synthetic pool (total demand 10). Jobs queue
    and admit as capacity frees; one job survives a worker crash
    mid-stream (after a completed checkpoint), another a live rescale,
    and a third melts its supervision step (injected job_tick delay) —
    which is deprioritized with JOB_TICK_OVERRUN while every neighbor
    keeps its heartbeat liveness (zero restarts outside the crashed job).
    EVERY job's goldens are byte-exact."""
    from arroyo_tpu import config as cfg, faults

    N = 10
    db = Database()
    cfg.update({"fleet.slots": 4, "fleet.tick-budget-ms": 150,
                "checkpoint.interval-ms": 150,
                "pipeline.worker-heartbeat-timeout-ms": 30_000,
                "testing.source-read-delay-micros": 3000})
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    jids, outs = [], []
    try:
        for i in range(N):
            sql, out = _sql(tmp_path, i)
            pid = db.create_pipeline(f"p{i}", sql, 1)
            jids.append(db.create_job(pid, tenant=f"t-{i % 2}"))
            outs.append(out)

        # wait until the pool is full and a backlog is visible
        deadline = time.monotonic() + 60
        queued_seen = 0
        running = []
        while time.monotonic() < deadline:
            states = {j: db.get_job(j)["state"] for j in jids}
            running = [j for j, s in states.items() if s == "Running"]
            queued = [j for j, s in states.items() if s == "Queued"]
            queued_seen = max(queued_seen, len(queued))
            if len(running) >= 3 and queued:
                break
            time.sleep(0.05)
        assert queued_seen >= 2, "no backlog formed on a 4-slot pool"
        fs = db.get_fleet_state() or {}
        assert sum((fs.get("queue_depth") or {}).values()) >= 1
        assert {e["tenant"] for e in fs.get("queue") or []} <= {"t-0", "t-1"}

        # melting job: its supervision step stalls 400ms per tick — the
        # budget must deprioritize it, not its neighbors
        melt = running[0]
        faults.install(f"job_tick:delay=400@match={melt}", seed=11)

        # crash: a different running job dies AFTER a completed checkpoint
        crash = running[1]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(c["state"] == "complete"
                   for c in db.list_checkpoints(crash)):
                break
            time.sleep(0.05)
        jc = ctl.jobs[crash]
        assert jc.handle is not None
        jc.handle.kill()

        # rescale: a third running job scales 1 -> 2 live
        rescale = running[2]
        db.update_job(rescale, desired_parallelism=2)

        for j in jids:
            assert ctl.wait_for_state(j, "Finished",
                                      timeout=240) == "Finished"
        faults.clear()

        # tick-budget isolation: the melting job was detected and
        # deprioritized...
        melt_codes = [e["code"] for e in db.list_events(melt)]
        assert "JOB_TICK_OVERRUN" in melt_codes, melt_codes
        # ...and no neighbor lost liveness because of it: zero restarts
        # and no WORKER_LOST anywhere but the crashed job
        for j in jids:
            if j == crash:
                assert int(db.get_job(j)["restarts"]) >= 1
                continue
            assert int(db.get_job(j)["restarts"] or 0) == 0, j
            assert "WORKER_LOST" not in [e["code"]
                                         for e in db.list_events(j)], j
        # the rescale landed while neighbors kept running
        assert db.get_pipeline(db.get_job(rescale)["pipeline_id"])[
            "parallelism"] == 2
        # admission decisions are on every queued job's feed
        sample = [j for j in jids
                  if "JOB_QUEUED" in [e["code"] for e in db.list_events(j)]]
        assert sample, "no job recorded a JOB_QUEUED decision"
        for j in sample:
            assert "JOB_ADMITTED" in [e["code"] for e in db.list_events(j)]

        # the one proof that matters: EVERY job byte-exact
        for out in outs:
            _assert_golden(out)
    finally:
        faults.clear()
        cfg.update({"checkpoint.interval-ms": 10_000,
                    "testing.source-read-delay-micros": 0})
        ctl.stop()
