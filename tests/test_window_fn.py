"""SQL OVER window functions: row_number/rank/dense_rank and partition
aggregates, bucketed by event timestamp, emitted on watermark."""

import numpy as np

from arroyo_tpu.batch import Batch, TIMESTAMP_FIELD
from arroyo_tpu.expr import Col
from arroyo_tpu.operators.base import OperatorContext
from arroyo_tpu.operators.window_fn import WindowFunctionOperator
from arroyo_tpu.state.tables import TableManager
from arroyo_tpu.types import TaskInfo, Watermark


class FakeCollector:
    def __init__(self):
        self.batches = []

    def collect(self, b):
        self.batches.append(b)

    def broadcast(self, s):
        pass


def rows_of(col):
    out = []
    for b in col.batches:
        out.extend(b.to_pylist())
    return out


def make_op(functions, partition=("g",), order_by=None):
    op = WindowFunctionOperator({
        "partition_fields": list(partition),
        "order_by": order_by if order_by is not None else [(Col("v"), False)],
        "functions": functions,
    })
    ti = TaskInfo("j", "wf", "window_function", 0, 1)
    ctx = OperatorContext(ti, None, TableManager(ti, "/tmp/wf-unused"))
    return op, ctx, FakeCollector()


def batch(ts, gs, vs):
    return Batch({
        TIMESTAMP_FIELD: np.array(ts, dtype=np.int64),
        "g": np.array(gs, dtype=object),
        "v": np.array(vs, dtype=np.int64),
    })


def test_row_number_desc_per_partition():
    op, ctx, col = make_op([("rn", "row_number", None)])
    op.process_batch(batch([100] * 6, ["a", "a", "a", "b", "b", "b"],
                           [5, 9, 1, 4, 8, 6]), ctx, col)
    op.handle_watermark(Watermark.event_time(101), ctx, col)
    rows = rows_of(col)
    got = {(r["g"], r["v"]): r["rn"] for r in rows}
    assert got == {("a", 9): 1, ("a", 5): 2, ("a", 1): 3,
                   ("b", 8): 1, ("b", 6): 2, ("b", 4): 3}


def test_rank_and_dense_rank_with_ties():
    op, ctx, col = make_op([("rk", "rank", None), ("dr", "dense_rank", None)])
    op.process_batch(batch([100] * 5, ["a"] * 5, [9, 9, 5, 5, 1]), ctx, col)
    op.on_close(ctx, col)
    rows = sorted(rows_of(col), key=lambda r: (-r["v"], r["rk"]))
    assert [(r["v"], r["rk"], r["dr"]) for r in rows] == [
        (9, 1, 1), (9, 1, 1), (5, 3, 2), (5, 3, 2), (1, 5, 3)]


def test_partition_aggregates():
    op, ctx, col = make_op([
        ("total", "sum", Col("v")), ("n", "count", None), ("avg_v", "avg", Col("v")),
    ])
    op.process_batch(batch([100] * 4, ["a", "a", "b", "b"], [1, 3, 10, 20]), ctx, col)
    op.on_close(ctx, col)
    rows = rows_of(col)
    for r in rows:
        if r["g"] == "a":
            assert r["total"] == 4 and r["n"] == 2 and r["avg_v"] == 2.0
        else:
            assert r["total"] == 30 and r["n"] == 2 and r["avg_v"] == 15.0


def test_buckets_independent():
    """Separate timestamps (separate windows) rank independently."""
    op, ctx, col = make_op([("rn", "row_number", None)])
    op.process_batch(batch([100, 100, 200, 200], ["a"] * 4, [5, 9, 7, 2]), ctx, col)
    op.on_close(ctx, col)
    rows = rows_of(col)
    got = {(r[TIMESTAMP_FIELD], r["v"]): r["rn"] for r in rows}
    assert got == {(100, 9): 1, (100, 5): 2, (200, 7): 1, (200, 2): 2}


def test_checkpoint_restore(tmp_path):
    storage = str(tmp_path / "wf")
    cfg = {
        "partition_fields": ["g"],
        "order_by": [(Col("v"), False)],
        "functions": [("rn", "row_number", None)],
    }
    ti = TaskInfo("j", "wf", "window_function", 0, 1)
    tm = TableManager(ti, storage)
    ctx = OperatorContext(ti, None, tm)
    op = WindowFunctionOperator(cfg)
    col = FakeCollector()
    op.process_batch(batch([100], ["a"], [5]), ctx, col)
    op.handle_checkpoint(None, ctx, col)
    tm.checkpoint(1, None)

    op2 = WindowFunctionOperator(cfg)
    tm2 = TableManager(ti, storage)
    tm2.restore(1, op2.tables())
    ctx2 = OperatorContext(ti, None, tm2)
    col2 = FakeCollector()
    op2.on_start(ctx2)
    op2.process_batch(batch([100], ["a"], [9]), ctx2, col2)
    op2.on_close(ctx2, col2)
    rows = rows_of(col2)
    got = {r["v"]: r["rn"] for r in rows}
    assert got == {9: 1, 5: 2}
