"""Mesh execution mode: TumblingAggregate over an 8-virtual-device CPU mesh.

The operator constructs a ShardedAggregator (keyed all_to_all exchange over
the mesh axis) instead of the single-chip SlotAggregator when
device.mesh-devices > 1 — the engine-integrated form of the multi-chip path
(VERDICT r3 item 2). Covers: end-to-end parity with the host oracle,
checkpoint/restore through the sharded state, and skew (one hot key)
degrading to local residency + spill instead of erroring.
"""

import numpy as np
import pytest

from arroyo_tpu.engine import Engine, run_graph
from arroyo_tpu.hashing import hash_column

from test_tumbling import expected_counts, windowed_count_graph

pytestmark = pytest.mark.mesh


def _mesh_devices():
    import jax

    return len(jax.devices())


@pytest.fixture
def _mesh_cfg(_storage):
    from arroyo_tpu import config as cfg

    if _mesh_devices() < 8:
        pytest.skip("needs 8 virtual devices (conftest sets XLA_FLAGS)")
    cfg.update({"device.mesh-devices": 8, "device.table-capacity": 1024,
                "device.batch-capacity": 256, "device.emit-capacity": 256,
                "device.spill-capacity": 256, "device.max-probes": 32})
    yield
    cfg.update({"device.mesh-devices": 0})


def test_mesh_tumbling_end_to_end_parity(_mesh_cfg):
    """Full pipeline through the engine with the sharded aggregator: output
    must equal the closed-form expectation (same as the single-chip runs)."""
    rows: list = []
    g = windowed_count_graph(rows, backend="jax", count=3000)
    run_graph(g, job_id="mesh-tw", timeout=120)
    got = {(r["window_start"] // 1_000_000, r["k"]): (r["cnt"], r["total"])
           for r in rows}
    assert got == expected_counts(count=3000)


def test_mesh_tumbling_checkpoint_restore(_mesh_cfg):
    """Checkpoint mid-stream, stop, restore into a fresh engine (sharded
    snapshot -> table -> sharded restore): merged output is exact."""
    rows2: list = []
    g2 = windowed_count_graph(rows2, backend="jax", count=4000)
    g2.nodes["src"].config["event_rate"] = 2000
    eng = Engine(g2, job_id="mesh-ckpt")
    eng.start()
    assert eng.checkpoint_and_wait(1, timeout=60)
    eng.stop()
    eng.join(timeout=60)

    rows3: list = []
    g3 = windowed_count_graph(rows3, backend="jax", count=4000)
    eng3 = Engine(g3, job_id="mesh-ckpt", restore_epoch=1)
    eng3.run_to_completion(timeout=120)
    merged = {}
    for r in rows2 + rows3:
        merged[(r["window_start"] // 1_000_000, r["k"])] = (r["cnt"], r["total"])
    assert merged == expected_counts(count=4000)


@pytest.mark.parametrize("name", ["tumbling_aggregates", "grouped_aggregates"])
def test_mesh_smoke_query_golden(name, _mesh_cfg, tmp_path):
    """A real SQL smoke query through the sharded path: plan -> engine with
    device.mesh-devices=8 -> output equals the golden file (the 'one smoke
    query produces correct output through the sharded path' gate)."""
    from test_smoke import assert_outputs, build, load_sql

    out = str(tmp_path / "out.json")
    eng = build(load_sql(name, out), 1, f"mesh-smoke-{name}")
    eng.run_to_completion(timeout=180)
    assert_outputs(name, out)


def test_mesh_skewed_hot_key_differential():
    """One hot key receiving most rows on 8 devices: per-destination send
    caps overflow, so partials stay resident on producing shards and the
    close-time host combine reconciles them — exact results, no error
    (VERDICT r3 item 6; previously fatal at parallel/sharded_agg.py:269)."""
    from arroyo_tpu.ops import DeviceHashAggregator
    from arroyo_tpu.parallel import ShardedAggregator, make_mesh

    if _mesh_devices() < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh(8)
    agg = ShardedAggregator(mesh, ("sum", "count"), (np.int64, np.int64),
                            cap=512, batch_cap=64, per_dest_cap=4,
                            max_probes=16, emit_cap=128, spill_cap=64)
    ora = DeviceHashAggregator(("sum", "count"), (np.int64, np.int64),
                               backend="numpy")
    rng = np.random.default_rng(3)
    for _ in range(6):
        n = 8 * 64
        raw = np.where(rng.random(n) < 0.9, 17, rng.integers(0, 40, size=n))
        keys = hash_column(raw.astype(np.int64))
        bins = rng.integers(0, 2, size=n).astype(np.int32)
        vals = rng.integers(1, 50, size=n).astype(np.int64)
        ones = np.ones(n, dtype=np.int64)
        agg.update(keys, bins, [vals, ones])
        ora.update(keys, bins, [vals, ones])
    sk, sb, sa = agg.extract_all(0, 10, 10)
    ok, ob, oa = ora.extract(0, 10, 10)
    to_dict = lambda K, B, A: {
        (int(b_), int(k_)): (int(A[0][i]), int(A[1][i]))
        for i, (k_, b_) in enumerate(zip(K.view(np.int64), B))
    }
    assert to_dict(sk, sb, sa) == to_dict(ok, ob, oa)


def test_mesh_table_pressure_spills_not_fatal():
    """More distinct groups than the probe table can absorb: the per-shard
    HBM spill buffer catches the remainder and extraction is exact."""
    from arroyo_tpu.ops import DeviceHashAggregator
    from arroyo_tpu.parallel import ShardedAggregator, make_mesh

    if _mesh_devices() < 4:
        pytest.skip("needs 4 virtual devices")
    mesh = make_mesh(4)
    # tiny table + tiny probe budget force placement failures
    agg = ShardedAggregator(mesh, ("count",), (np.int64,),
                            cap=64, batch_cap=128, per_dest_cap=128,
                            max_probes=2, emit_cap=64, spill_cap=512)
    ora = DeviceHashAggregator(("count",), (np.int64,), backend="numpy")
    rng = np.random.default_rng(5)
    for _ in range(3):
        n = 4 * 128
        keys = hash_column(rng.integers(0, 400, size=n).astype(np.int64))
        bins = np.zeros(n, dtype=np.int32)
        ones = np.ones(n, dtype=np.int64)
        agg.update(keys, bins, [ones])
        ora.update(keys, bins, [ones])
    sk, sb, sa = agg.extract_all(0, 10, 10)
    ok, ob, oa = ora.extract(0, 10, 10)
    got = {int(k_): int(sa[0][i]) for i, k_ in enumerate(sk.view(np.int64))}
    want = {int(k_): int(oa[0][i]) for i, k_ in enumerate(ok.view(np.int64))}
    assert got == want


def test_mesh_sliding_end_to_end_parity(_mesh_cfg, tmp_path):
    """SlidingAggregate over the 8-device mesh: the nexmark_q5-style hop
    query through the engine must match its golden output."""
    from test_smoke import assert_outputs, build, load_sql

    out = str(tmp_path / "out.json")
    eng = build(load_sql("sliding_window", out), 1, "mesh-sliding")
    eng.run_to_completion(timeout=180)
    assert_outputs("sliding_window", out)


def test_mesh_sliding_checkpoint_restore(_mesh_cfg, tmp_path):
    """Sharded sliding state checkpoints and restores exactly."""
    import numpy as np

    from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
    from arroyo_tpu.engine import Engine
    from arroyo_tpu.expr import BinOp, Col, Lit
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])

    def mk(rows, count=4000):
        g = Graph()
        g.add_node(Node("src", OpName.SOURCE, {
            "connector": "impulse", "message_count": count,
            "interval_micros": 1000, "start_time_micros": 0,
            "event_rate": 2000}, 1))
        g.add_node(Node("wm", OpName.WATERMARK, {"expr": Col(TIMESTAMP_FIELD)}, 1))
        g.add_node(Node("key", OpName.KEY, {
            "keys": [("k", BinOp("%", Col("counter"), Lit(5)))]}, 1))
        g.add_node(Node("agg", OpName.SLIDING_AGGREGATE, {
            "width_micros": 1_000_000, "slide_micros": 250_000,
            "key_fields": ["k"],
            "aggregates": [("cnt", "count", None)],
            "input_dtype_of": lambda e: np.dtype(np.int64)}, 1))
        g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
        for a, b, t in [("src", "wm", "f"), ("wm", "key", "f"),
                        ("key", "agg", "s"), ("agg", "sink", "f")]:
            g.add_edge(a, b, EdgeType.FORWARD if t == "f" else EdgeType.SHUFFLE, S)
        return g

    rows2: list = []
    eng = Engine(mk(rows2), job_id="mesh-slide-ckpt")
    eng.start()
    assert eng.checkpoint_and_wait(1, timeout=60)
    eng.stop()
    eng.join(timeout=60)
    rows3: list = []
    eng3 = Engine(mk(rows3), job_id="mesh-slide-ckpt", restore_epoch=1)
    eng3.run_to_completion(timeout=120)

    merged = {}
    for r in rows2 + rows3:
        merged[(r["window_start"], r["k"])] = r["cnt"]
    # oracle: event c at ts=c*1000 lands in windows starting
    # (ts//250ms - j)*250ms for j in 0..3
    want: dict = {}
    for c in range(4000):
        ts = c * 1000
        sb = (ts // 250_000) * 250_000
        for j in range(4):
            want[(sb - j * 250_000, c % 5)] = want.get((sb - j * 250_000, c % 5), 0) + 1
    assert merged == want
