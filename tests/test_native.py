"""C++ host runtime: differential tests against the NumPy reference paths.

Covers cpp/arroyo_host.cc via arroyo_tpu.native: hashing, repartition
permutation, JSON-lines parsing, the framed TCP data plane, and the
columnar wire codec.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np
import pytest

from arroyo_tpu import native
from arroyo_tpu.batch import TIMESTAMP_FIELD, Batch, Schema
from arroyo_tpu.hashing import hash_columns, servers_for_hashes, splitmix64
from arroyo_tpu.native.wire import (
    decode_batch,
    decode_signal,
    encode_batch,
    encode_signal,
)
from arroyo_tpu.types import CheckpointBarrier, Signal, Watermark

# Lazily skip at setup time, NOT at collection time: native.available()
# builds+loads the .so, and a native-layer fault at import poisoned the
# whole suite in round 3 (VERDICT.md). A fixture keeps collection pure.
@pytest.fixture(autouse=True)
def _require_native(request):
    if request.node.get_closest_marker("no_native_required"):
        return
    if not native.available():
        pytest.skip("native library unavailable (no g++?)")

rng = np.random.default_rng(7)


@pytest.mark.no_native_required
def test_incompatible_so_falls_back_to_numpy(tmp_path):
    """A library that loads but is missing symbols (stale/half-built .so —
    the exact failure mode that shipped in round 3) must degrade to the
    NumPy fallback, not crash. No fixture: this test must run even when the
    real library is unavailable."""
    import os
    import shutil
    import subprocess
    import sys

    if shutil.which("g++") is None:
        pytest.skip("g++ unavailable")
    src = tmp_path / "empty.cc"
    src.write_text('extern "C" { void ah_not_the_api(void) {} }\n')
    so = tmp_path / "libarroyo_host.so"
    subprocess.run(
        ["g++", "-shared", "-fPIC", "-o", str(so), str(src)], check=True
    )
    code = (
        "import arroyo_tpu.native as n\n"
        f"n._LIB_PATH = {str(so)!r}\n"
        "n._CPP_DIR = ''\n"  # no sources next to it -> no rebuild attempt
        "assert n.lib() is None\n"
        "assert not n.available()\n"
        "import numpy as np\n"
        "from arroyo_tpu.hashing import hash_columns\n"
        "h = hash_columns([np.arange(10, dtype=np.int64)])\n"
        "assert h.shape == (10,)\n"
        "print('FALLBACK_OK')\n"
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=repo_root, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "FALLBACK_OK" in r.stdout


def test_hash_u64_matches_numpy():
    x = rng.integers(0, 1 << 63, size=10_000, dtype=np.uint64)
    assert np.array_equal(native.hash_u64(x), splitmix64(x))


def test_hash_f64_matches_numpy():
    x = rng.normal(size=5000)
    x[::100] = 0.0
    x[1::100] = -0.0
    want = splitmix64(np.where(x == 0.0, 0.0, x).astype(np.float64).view(np.uint64))
    assert np.array_equal(native.hash_f64(x), want)


def test_hash_combine_matches_numpy():
    a = rng.integers(0, 1 << 63, size=1000, dtype=np.uint64)
    b = rng.integers(0, 1 << 63, size=1000, dtype=np.uint64)
    want = splitmix64(a ^ (b + np.uint64(0x9E3779B97F4A7C15)))
    assert np.array_equal(native.hash_combine(a, b), want)


def test_hash_columns_uses_native_consistently():
    """hash_columns output must be identical with and without the native
    path (checkpoint rescale depends on hash stability)."""
    from arroyo_tpu import config as cfg

    cols = [
        rng.integers(0, 1000, size=2000).astype(np.int64),
        rng.normal(size=2000),
        np.array([f"s{i % 17}" for i in range(2000)], dtype=object),
    ]
    with_native = hash_columns(cols)
    import arroyo_tpu.native as nat

    saved = nat._lib, nat._lib_failed
    nat._lib, nat._lib_failed = None, True  # force the numpy fallback
    try:
        without = hash_columns(cols)
    finally:
        nat._lib, nat._lib_failed = saved
    assert np.array_equal(with_native, without)


def test_partition_matches_argsort():
    h = rng.integers(0, (1 << 64) - 1, size=20_000, dtype=np.uint64)
    for n in (1, 2, 3, 7, 16):
        out = native.partition(h, n)
        assert out is not None
        perm, offsets = out
        dests = servers_for_hashes(h, n)
        order = np.argsort(dests, kind="stable")
        bounds = np.searchsorted(dests[order], np.arange(n + 1))
        assert np.array_equal(perm, order), f"n={n}"
        assert np.array_equal(offsets, bounds), f"n={n}"


def test_parse_json_lines_matches_python():
    rows = []
    for i in range(500):
        rows.append({
            "a": i, "b": i * 0.5, "ok": i % 3 == 0,
            "s": f"val_{i}" if i % 10 else None,
            "extra": {"nested": [1, 2, {"x": "y"}]},
        })
    data = "\n".join(json.dumps(r) for r in rows).encode()
    fields = [("a", "int64"), ("b", "float64"), ("ok", "bool"), ("s", "string")]
    cols = native.parse_json_lines(data, fields, max_rows=1000)
    assert cols is not None
    assert list(cols["a"]) == [r["a"] for r in rows]
    assert np.allclose(cols["b"], [r["b"] for r in rows])
    assert list(cols["ok"]) == [r["ok"] for r in rows]
    # python side maps None -> empty string in native parser
    assert [s for s in cols["s"][:20]] == [
        (r["s"] if r["s"] is not None else "") for r in rows[:20]
    ]


def test_parse_json_lines_escapes_and_unicode():
    data = json.dumps({"s": 'he said "hi"\n\tümlaut ☃', "a": -42}).encode()
    cols = native.parse_json_lines(data, [("s", "string"), ("a", "int64")], 10)
    assert cols is not None
    assert cols["s"][0] == 'he said "hi"\n\tümlaut ☃'
    assert cols["a"][0] == -42


def test_parse_json_lines_malformed_returns_none():
    assert native.parse_json_lines(b"not json", [("a", "int64")], 10) is None


def test_data_plane_roundtrip():
    from arroyo_tpu.native import DataPlaneConn, DataPlaneListener, MSG_DATA, MSG_SIGNAL

    listener = DataPlaneListener()
    received = []

    def server():
        conn = listener.accept()
        while True:
            got = conn.recv()
            if got is None:
                break
            received.append(got)
        conn.close()

    t = threading.Thread(target=server, daemon=True)
    t.start()
    client = DataPlaneConn.connect("127.0.0.1", listener.port)
    batch = Batch({
        "x": np.arange(1000, dtype=np.int64),
        "name": np.array([f"n{i}" if i % 7 else None for i in range(1000)], dtype=object),
        TIMESTAMP_FIELD: np.arange(1000, dtype=np.int64) * 1000,
    })
    client.send((1, 0, 2, 3), MSG_DATA, encode_batch(batch))
    client.send((1, 0, 2, 3), MSG_SIGNAL,
                encode_signal(Signal.barrier_of(CheckpointBarrier(5, 1, 99, True))))
    client.send((1, 0, 2, 3), MSG_SIGNAL,
                encode_signal(Signal.watermark_of(Watermark.event_time(123456))))
    client.close()
    t.join(timeout=10)
    listener.close()
    assert len(received) == 3
    quad, mtype, payload = received[0]
    assert quad == (1, 0, 2, 3) and mtype == MSG_DATA
    out = decode_batch(payload)
    assert np.array_equal(out["x"], batch["x"])
    assert out["name"][0] is None and out["name"][1] == "n1"
    sig = decode_signal(received[1][2])
    assert sig.barrier.epoch == 5 and sig.barrier.then_stop
    sig2 = decode_signal(received[2][2])
    assert sig2.watermark.value == 123456


def test_wire_codec_dtypes():
    b = Batch({
        "i32": np.arange(10, dtype=np.int32),
        "u64": np.arange(10, dtype=np.uint64),
        "f32": np.linspace(0, 1, 10, dtype=np.float32),
        "bools": np.array([True, False] * 5),
        TIMESTAMP_FIELD: np.arange(10, dtype=np.int64),
    })
    out = decode_batch(encode_batch(b))
    for name in b.columns:
        assert out[name].dtype == b[name].dtype
        assert np.array_equal(out[name], b[name])


def test_two_worker_engine_over_data_plane(tmp_path, _storage):
    """Split one dataflow across two Engine instances ('workers') connected
    by the C++ data plane: worker 0 runs the source, worker 1 runs the keyed
    aggregate + sink; shuffle and barriers/watermarks cross the wire."""
    import arroyo_tpu
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.engine.network import NetworkManager
    from arroyo_tpu.expr import Col
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    arroyo_tpu._load_operators()
    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    rows: list = []

    def build_graph():
        g = Graph()
        g.add_node(Node("src", OpName.SOURCE, {
            "connector": "impulse", "message_count": 300,
            "interval_micros": 100_000, "start_time_micros": 0}, 1))
        g.add_node(Node("wm", OpName.WATERMARK, {"expr": Col(TIMESTAMP_FIELD)}, 1))
        g.add_node(Node("key", OpName.KEY, {
            "keys": [("g", __import__("arroyo_tpu.expr", fromlist=["BinOp"]).BinOp(
                "%", Col("counter"), __import__("arroyo_tpu.expr", fromlist=["Lit"]).Lit(3)))]}, 1))
        g.add_node(Node("agg", OpName.TUMBLING_AGGREGATE, {
            "width_micros": 10_000_000, "key_fields": ["g"],
            "aggregates": [("n", "count", None)],
            "backend": "numpy"}, 2))
        g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
        g.add_edge("src", "wm", EdgeType.FORWARD, S)
        g.add_edge("wm", "key", EdgeType.FORWARD, S)
        g.add_edge("key", "agg", EdgeType.SHUFFLE, S)
        g.add_edge("agg", "sink", EdgeType.FORWARD, S)
        return g

    assignment = {
        ("src", 0): 0, ("wm", 0): 0, ("key", 0): 0,
        ("agg", 0): 1, ("agg", 1): 1, ("sink", 0): 1,
    }
    nm0 = NetworkManager()
    nm1 = NetworkManager()
    peers = {0: ("127.0.0.1", nm0.port), 1: ("127.0.0.1", nm1.port)}
    nm0.set_peers(peers)
    nm1.set_peers(peers)
    w0 = Engine(build_graph(), job_id="dist", assignment=assignment,
                worker_index=0, network=nm0)
    w1 = Engine(build_graph(), job_id="dist", assignment=assignment,
                worker_index=1, network=nm1)
    w1.build(); w0.build()
    w1.start(); w0.start()
    w0.join(timeout=120)
    w1.join(timeout=120)
    nm0.close(); nm1.close()
    total = sum(r["n"] for r in rows)
    assert total == 300
    per_g = {}
    for r in rows:
        per_g[r["g"]] = per_g.get(r["g"], 0) + r["n"]
    assert per_g == {0: 100, 1: 100, 2: 100}


@pytest.mark.parametrize("target", ["asan-test", "tsan-test"])
def test_cpp_host_under_sanitizers(target):
    """The C++ host runtime passes its full-surface harness under ASan/
    UBSan and TSan (SURVEY §5: sanitizers stand in for the reference's
    Rust ownership guarantees; covers the threaded data plane)."""
    import subprocess

    cpp = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "cpp")
    r = subprocess.run(["make", "-C", cpp, target],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"{target} failed:\n{r.stdout}\n{r.stderr}"
    assert "host_test OK" in r.stdout
