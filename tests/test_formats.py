"""Format layer: avro / protobuf / raw / framing / debezium / bad-data.

Reference test model: round-trip tests in crates/arroyo-formats
(avro de/ser, proto/test/, framing in de.rs tests).
"""

from __future__ import annotations

import json
import os
import subprocess

import numpy as np
import pytest

from arroyo_tpu.batch import TIMESTAMP_FIELD, Batch, Schema
from arroyo_tpu.formats.avro_fmt import (
    AvroSchema,
    decode_confluent,
    decode_datum,
    encode_confluent,
    encode_datum,
    read_ocf,
    write_ocf,
)
from arroyo_tpu.formats.framing import frame_iter, frame_join
from arroyo_tpu.formats.registry import make_deserializer, serialize_batch
from arroyo_tpu.formats.schema_registry import InMemorySchemaRegistry

AVRO_SCHEMA = {
    "type": "record",
    "name": "Bid",
    "fields": [
        {"name": "auction", "type": "long"},
        {"name": "price", "type": "double"},
        {"name": "bidder", "type": ["null", "string"]},
        {"name": "fast", "type": "boolean"},
        {"name": "ts", "type": {"type": "long", "logicalType": "timestamp-micros"}},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
    ],
}

ROWS = [
    {"auction": 1001, "price": 2.5, "bidder": "alice", "fast": True,
     "ts": 1696871600000000, "tags": ["a", "b"]},
    {"auction": -7, "price": -0.25, "bidder": None, "fast": False,
     "ts": 1696871600500000, "tags": []},
]


def test_avro_datum_roundtrip():
    sch = AvroSchema(AVRO_SCHEMA)
    for row in ROWS:
        assert decode_datum(sch, encode_datum(sch, row)) == row


def test_avro_confluent_wire_format():
    sch = AvroSchema(AVRO_SCHEMA)
    msg = encode_confluent(sch, 42, ROWS[0])
    sid, row = decode_confluent(sch, msg)
    assert sid == 42 and row == ROWS[0]


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_avro_ocf_roundtrip(codec):
    sch = AvroSchema(AVRO_SCHEMA)
    data = write_ocf(sch, ROWS, codec=codec)
    sch2, rows = read_ocf(data)
    assert rows == ROWS
    assert sch2.field_names() == sch.field_names()


def test_avro_timestamp_millis_normalized():
    sch = AvroSchema({
        "type": "record", "name": "R",
        "fields": [{"name": "t", "type": {"type": "long", "logicalType": "timestamp-millis"}}],
    })
    out = decode_datum(sch, encode_datum(sch, {"t": 1696871600123000}))
    assert out["t"] == 1696871600123000  # stays micros through the round trip


def test_avro_deserializer_builds_batches():
    sch = Schema.of([
        ("auction", "int64"), ("price", "float64"), ("bidder", "string"),
        ("fast", "bool"), ("ts", "timestamp"), (TIMESTAMP_FIELD, "int64"),
    ])
    de = make_deserializer(
        {"format": "avro", "avro.schema": json.dumps(AVRO_SCHEMA),
         "event_time_field": "ts"},
        sch,
    )
    asch = AvroSchema(AVRO_SCHEMA)
    for r in ROWS:
        de.deserialize(encode_datum(asch, r))
    b = de.flush()
    assert b.num_rows == 2
    assert list(b["auction"]) == [1001, -7]
    assert list(b.timestamps) == [r["ts"] for r in ROWS]
    assert b["bidder"][1] is None


def test_framing_newline_and_length():
    msgs = [b"one", b"two", b"three"]
    assert list(frame_iter(frame_join(msgs, "newline"), "newline")) == msgs
    assert list(frame_iter(frame_join(msgs, "length"), "length")) == msgs
    assert list(frame_iter(b"solo", None)) == [b"solo"]
    with pytest.raises(ValueError):
        list(frame_iter(b"\x00\x00\x00\x09abc", "length"))  # overrun


def test_raw_string_roundtrip():
    sch = Schema.of([("value", "string"), (TIMESTAMP_FIELD, "int64")])
    de = make_deserializer({"format": "raw_string"}, sch)
    de.deserialize(b"hello", timestamp_micros=5)
    de.deserialize("world", timestamp_micros=6)
    b = de.flush()
    assert list(b["value"]) == ["hello", "world"]
    out = serialize_batch({"format": "raw_string"}, b, sch)
    assert out == [b"hello", b"world"]


def test_debezium_json_to_updating_rows():
    sch = Schema.of([
        ("id", "int64"), ("v", "int64"), ("_is_retract", "bool"),
        (TIMESTAMP_FIELD, "int64"),
    ])
    de = make_deserializer({"format": "debezium_json"}, sch)
    de.deserialize(json.dumps({"op": "c", "before": None, "after": {"id": 1, "v": 10}}),
                   timestamp_micros=1)
    de.deserialize(json.dumps({"op": "u", "before": {"id": 1, "v": 10},
                               "after": {"id": 1, "v": 11}}), timestamp_micros=2)
    de.deserialize(json.dumps({"op": "d", "before": {"id": 1, "v": 11}, "after": None}),
                   timestamp_micros=3)
    b = de.flush()
    assert list(b["_is_retract"]) == [False, True, False, True]
    assert list(b["v"]) == [10, 10, 11, 11]


def test_bad_data_drop_vs_fail():
    sch = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    de = make_deserializer({"format": "json", "bad_data": "drop"}, sch)
    de.deserialize(b"not json", timestamp_micros=1)
    de.deserialize(json.dumps({"x": 1}), timestamp_micros=1)
    b = de.flush()
    assert b.num_rows == 1 and de.errors == 1
    de2 = make_deserializer({"format": "json", "bad_data": "fail"}, sch)
    with pytest.raises(Exception):
        de2.deserialize(b"not json")


def test_protobuf_roundtrip(tmp_path):
    import shutil

    if shutil.which("protoc") is None:
        pytest.skip("protoc not installed; descriptor compilation unavailable")
    proto = tmp_path / "bid.proto"
    proto.write_text(
        'syntax = "proto3";\n'
        "message Bid { int64 auction = 1; double price = 2; string bidder = 3; }\n"
    )
    desc = tmp_path / "bid.desc"
    subprocess.run(
        ["protoc", f"--descriptor_set_out={desc}", "--proto_path", str(tmp_path),
         str(proto)],
        check=True,
    )
    sch = Schema.of([
        ("auction", "int64"), ("price", "float64"), ("bidder", "string"),
        (TIMESTAMP_FIELD, "int64"),
    ])
    cfg = {"format": "protobuf", "proto.descriptor_file": str(desc),
           "proto.message_name": "Bid"}
    rows = [{"auction": 5, "price": 1.5, "bidder": "bob"},
            {"auction": 6, "price": 0.0, "bidder": ""}]
    b_in = Batch({
        "auction": np.array([5, 6], dtype=np.int64),
        "price": np.array([1.5, 0.0]),
        "bidder": np.array(["bob", ""], dtype=object),
        TIMESTAMP_FIELD: np.array([1, 2], dtype=np.int64),
    })
    msgs = serialize_batch(cfg, b_in, sch)
    de = make_deserializer(cfg, sch)
    for m in msgs:
        de.deserialize(m, timestamp_micros=9)
    b = de.flush()
    assert list(b["auction"]) == [5, 6]
    assert list(b["bidder"]) == ["bob", ""]
    assert b["price"][0] == 1.5


def test_in_memory_schema_registry():
    reg = InMemorySchemaRegistry()
    sid = reg.register("bids-value", json.dumps(AVRO_SCHEMA))
    assert reg.get_schema_by_id(sid) == json.dumps(AVRO_SCHEMA)
    assert reg.get_latest("bids-value") == (sid, json.dumps(AVRO_SCHEMA))
    assert reg.register("other", json.dumps(AVRO_SCHEMA)) == sid  # dedup


def test_sql_pipeline_with_raw_string_format(tmp_path, _storage):
    """SQL DDL format option drives the registry end-to-end."""
    import arroyo_tpu
    from arroyo_tpu.engine.engine import run_graph
    from arroyo_tpu.sql import plan_query

    arroyo_tpu._load_operators()
    inp = tmp_path / "lines.txt"
    inp.write_text("apple\nbanana\navocado\n")
    sql = f"""
    CREATE TABLE lines (value TEXT) WITH (
      connector = 'single_file', path = '{inp}', format = 'raw_string',
      type = 'source');
    SELECT upper(value) AS shout FROM lines WHERE value LIKE 'a%';
    """
    pp = plan_query(sql)
    run_graph(pp.graph, job_id="raw", timeout=60)
    assert sorted(r["shout"] for r in pp.sinks[0].rows) == ["APPLE", "AVOCADO"]
