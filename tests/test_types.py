import numpy as np
import pytest

from arroyo_tpu.batch import Batch, Schema, Field, TIMESTAMP_FIELD
from arroyo_tpu.engine.task import WatermarkHolder
from arroyo_tpu.hashing import hash_column, hash_columns, servers_for_hashes
from arroyo_tpu.types import (
    U64_MAX,
    Watermark,
    range_for_server,
    server_for_hash,
)


def test_key_ranges_partition_the_space():
    for n in (1, 2, 3, 7, 16):
        ranges = [range_for_server(i, n) for i in range(n)]
        assert ranges[0][0] == 0
        assert ranges[-1][1] == U64_MAX
        for (s0, e0), (s1, e1) in zip(ranges, ranges[1:]):
            assert e0 + 1 == s1
        for h in (0, 1, 12345, U64_MAX // 2, U64_MAX - 1, U64_MAX):
            owner = server_for_hash(h, n)
            lo, hi = ranges[owner]
            assert lo <= h <= hi


def test_servers_for_hashes_matches_scalar():
    hashes = np.array([0, 1, 999, U64_MAX // 3, U64_MAX], dtype=np.uint64)
    for n in (1, 2, 5, 8):
        vec = servers_for_hashes(hashes, n)
        for h, s in zip(hashes.tolist(), vec.tolist()):
            assert s == server_for_hash(h, n)


def test_hashing_deterministic_and_spread():
    col = np.arange(1000, dtype=np.int64)
    h1, h2 = hash_column(col), hash_column(col)
    assert (h1 == h2).all()
    assert len(np.unique(h1)) == 1000
    servers = servers_for_hashes(h1, 4)
    counts = np.bincount(servers, minlength=4)
    assert counts.min() > 150  # roughly uniform

    strs = np.array(["a", "b", "a", None if False else "c"], dtype=object)
    hs = hash_column(strs)
    assert hs[0] == hs[2] and hs[0] != hs[1]

    multi = hash_columns([col, col])
    assert (hash_columns([col, col]) == multi).all()
    assert not (multi == h1).all()


def test_watermark_holder_min_merge_and_idle():
    h = WatermarkHolder(3)
    assert h.merged() is None
    h.set(0, Watermark.event_time(100))
    h.set(1, Watermark.event_time(50))
    assert h.merged() is None  # input 2 unseen
    h.set(2, Watermark.idle())
    assert h.merged() == Watermark.event_time(50)
    h.set(1, Watermark.event_time(200))
    assert h.merged() == Watermark.event_time(100)
    h.remove(0)
    assert h.merged() == Watermark.event_time(200)
    h.set(1, Watermark.idle())
    h.set(2, Watermark.idle())
    assert h.merged().is_idle


def test_batch_ops():
    b = Batch({"a": np.array([1, 2, 3]), TIMESTAMP_FIELD: np.array([10, 20, 30])})
    assert len(b) == 3
    assert b.filter(np.array([True, False, True])).num_rows == 2
    assert b.slice(1, 3)["a"].tolist() == [2, 3]
    c = Batch.concat([b, b])
    assert c.num_rows == 6
    with pytest.raises(ValueError):
        Batch({"a": np.array([1]), "b": np.array([1, 2])})


def test_schema_roundtrip():
    s = Schema.of([("x", "int64"), ("s", "string"), (TIMESTAMP_FIELD, "int64")],
                  key_fields=("x",), has_keys=True)
    assert Schema.from_json(s.to_json()) == s
