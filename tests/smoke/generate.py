#!/usr/bin/env python
"""Generate smoke-test inputs and golden outputs.

The golden outputs are computed by independent plain-Python oracles (dict
loops, no engine code), mirroring how the reference pins behavior with
golden files (crates/arroyo-sql-testing/golden_outputs). Re-run after
changing inputs or adding queries:  python tests/smoke/generate.py
"""

from __future__ import annotations

import json
import os
import sys
from collections import defaultdict
from datetime import datetime, timezone

HERE = os.path.dirname(os.path.abspath(__file__))
INPUTS = os.path.join(HERE, "inputs")
GOLDEN = os.path.join(HERE, "golden")

BASE = 1696871600 * 1_000_000  # 2023-10-09T17:13:20Z
S = 1_000_000


def iso(us: int) -> str:
    dt = datetime.fromtimestamp(us // S, tz=timezone.utc)
    base = dt.strftime("%Y-%m-%dT%H:%M:%S")
    frac = us % S
    if frac == 0:
        return base
    if frac % 1000 == 0:
        return f"{base}.{frac // 1000:03d}"
    return f"{base}.{frac:06d}"


def iso_tz(us: int) -> str:
    return iso(us) + "+00:00"


# --------------------------------------------------------------------------
# inputs


def gen_impulse():
    rows = []
    for i in range(400):
        ts = BASE + i * 200_000
        rows.append({"timestamp": iso_tz(ts), "counter": i, "subtask_index": 0})
    return rows


def gen_cars():
    rows = []
    for i in range(300):
        ts = BASE + i * 250_000
        rows.append({
            "timestamp": iso_tz(ts),
            "driver_id": 100 + i % 7,
            "event_type": "pickup" if i % 2 == 0 else "dropoff",
            "location": f"loc_{i % 5}",
        })
    return rows


def gen_bids():
    rows = []
    for i in range(600):
        ts = BASE + i * 100_000
        rows.append({
            "datetime": iso_tz(ts),
            "auction": 1000 + ((i * 7) % 5) * 100,
            "price": (i * 13) % 1000 + 1,
            "bidder": f"b{i % 11}",
        })
    return rows


def gen_orders():
    rows = []
    for i in range(120):
        ts = BASE + i * 500_000
        rows.append({
            "timestamp": iso_tz(ts),
            "order_id": i,
            "customer_id": i % 10,
            "amount": (i * 37) % 500,
        })
    return rows


def gen_spill_users():
    """Wide-keyspace event stream for the tiered-state smoke family: ~1200
    distinct users over 4000 rows, sized so a few-tens-of-KB spill budget
    is ~10x smaller than the resident keyed state."""
    rows = []
    for i in range(4000):
        ts = BASE + i * 50_000
        rows.append({
            "timestamp": iso_tz(ts),
            "user_id": (i * 37) % 1200,
            "amount": (i * 13) % 500,
        })
    return rows


def gen_customers():
    rows = []
    for i in range(15):
        ts = BASE + i * 3_000_000
        rows.append({"timestamp": iso_tz(ts), "customer_id": i, "name": f"cust_{i}"})
    return rows


def gen_aggregate_updates():
    """Debezium envelope stream over an orders table (id pk): creates,
    updates (quantity/status churn), deletes — deterministic."""
    envs = []
    state = {}
    products = ["widget", "gadget", "sprocket"]
    for i in range(60):
        row = {
            "id": i, "customer_name": f"cust_{i % 8}",
            "product_name": products[i % 3], "quantity": (i * 7) % 20 + 1,
            "price": round(9.99 + (i % 5) * 2.5, 2), "status": "new",
        }
        envs.append({"before": None, "after": row, "op": "c"})
        state[i] = row
    for i in range(0, 60, 4):  # update every 4th order
        before = dict(state[i])
        after = dict(before, quantity=before["quantity"] + 3, status="shipped")
        envs.append({"before": before, "after": after, "op": "u"})
        state[i] = after
    for i in range(0, 60, 10):  # delete every 10th
        envs.append({"before": dict(state[i]), "after": None, "op": "d"})
        del state[i]
    return envs, state


def input_ts(row, field):
    s = row[field].replace("+00:00", "")
    dt = datetime.fromisoformat(s).replace(tzinfo=timezone.utc)
    return int(dt.timestamp() * S)


# --------------------------------------------------------------------------
# window helpers


def tumble_start(ts: int, width: int) -> int:
    return (ts // width) * width


def hop_starts(ts: int, slide: int, width: int):
    first = ((ts - width) // slide + 1) * slide
    if first > ts:
        first -= slide
    starts = []
    s = max(first, ((ts - width) // slide + 1) * slide)
    # all starts s with s <= ts < s + width, s multiple of slide
    k = (ts - width) // slide + 1
    while k * slide <= ts:
        if ts < k * slide + width:
            starts.append(k * slide)
        k += 1
    return starts


def sessions(ts_list: list[int], gap: int):
    """Sorted event times -> list of (start, end, count-slice indices)."""
    out = []
    cur = None
    for t in sorted(ts_list):
        if cur is None or t - cur[1] > gap:
            if cur is not None:
                out.append(cur)
            cur = [t, t, 1]
        else:
            cur[1] = t
            cur[2] += 1
    if cur is not None:
        out.append(cur)
    return [(s, e + gap, n) for s, e, n in out]


# --------------------------------------------------------------------------
# oracles (one per query)


def o_select_star(ins):
    return [dict(r, timestamp=iso(input_ts(r, "timestamp"))) for r in ins["cars"]]


def o_expressions(ins):
    out = []
    for r in ins["impulse"]:
        c = r["counter"]
        if not (10 <= c < 60):
            continue
        if 30 <= c <= 39:
            continue
        out.append({
            "c": c,
            "doubled": c * 2,
            "parity": "even" if c % 2 == 0 else "odd",
            "clamped": c ** 0.5,
            "label": f"row_{c}",
        })
    return out


def o_tumbling_aggregates(ins):
    W = 10 * S
    byw = defaultdict(list)
    for r in ins["impulse"]:
        byw[tumble_start(input_ts(r, "timestamp"), W)].append(r["counter"])
    out = []
    for w, cs in sorted(byw.items()):
        out.append({
            "start": iso(w), "end": iso(w + W), "rows": len(cs),
            "total": sum(cs), "min_c": min(cs), "max_c": max(cs),
            "avg_c": sum(cs) / len(cs),
        })
    return out


def o_grouped_aggregates(ins):
    W = 10 * S
    byk = defaultdict(list)
    for r in ins["impulse"]:
        k = (tumble_start(input_ts(r, "timestamp"), W), r["counter"] % 3)
        byk[k].append(r["counter"])
    return [
        {"start": iso(w), "g": g, "rows": len(cs), "total": sum(cs)}
        for (w, g), cs in sorted(byk.items())
    ]


def o_sliding_window(ins):
    slide, width = 2 * S, 10 * S
    byk = defaultdict(list)
    for r in ins["bids"]:
        ts = input_ts(r, "datetime")
        for s in hop_starts(ts, slide, width):
            byk[(s, r["auction"])].append(r["price"])
    return [
        {"start": iso(s), "end": iso(s + width), "auction": a,
         "bids": len(ps), "top_price": max(ps)}
        for (s, a), ps in sorted(byk.items())
    ]


def o_session_window(ins):
    gap = 20 * S
    byu = defaultdict(list)
    for r in ins["impulse"]:
        u = 0 if r["counter"] % 10 == 0 else r["counter"]
        byu[u].append(input_ts(r, "timestamp"))
    out = []
    for u, ts_list in sorted(byu.items()):
        for s, e, n in sessions(ts_list, gap):
            out.append({"start": iso(s), "end": iso(e), "user_id": u, "rows": n})
    return out


def _hop_counts(bids):
    slide, width = 2 * S, 10 * S
    byk = defaultdict(int)
    for r in bids:
        ts = input_ts(r, "datetime")
        for s in hop_starts(ts, slide, width):
            byk[(s, r["auction"])] += 1
    return byk


def o_nexmark_q5(ins):
    byk = _hop_counts(ins["bids"])
    maxn = defaultdict(int)
    for (w, _a), n in byk.items():
        maxn[w] = max(maxn[w], n)
    return [
        {"auction": a, "count": n}
        for (w, a), n in sorted(byk.items())
        if n >= maxn[w]
    ]


def o_windowed_inner_join(ins):
    W = 20 * S
    pick = defaultdict(int)
    drop = defaultdict(int)
    for r in ins["cars"]:
        k = (tumble_start(input_ts(r, "timestamp"), W), r["driver_id"])
        if r["event_type"] == "pickup":
            pick[k] += 1
        else:
            drop[k] += 1
    out = []
    for (w, d), p in sorted(pick.items()):
        if (w, d) in drop:
            out.append({"start": iso(w), "driver_id": d, "pickups": p,
                        "dropoffs": drop[(w, d)]})
    return out


def o_windowed_full_join(ins):
    W = 20 * S
    pick = defaultdict(int)
    drop = defaultdict(int)
    for r in ins["cars"]:
        k = (tumble_start(input_ts(r, "timestamp"), W), r["driver_id"])
        if r["event_type"] == "pickup" and r["driver_id"] % 2 == 0:
            pick[k] += 1
        if r["event_type"] == "dropoff" and r["driver_id"] % 3 == 0:
            drop[k] += 1
    out = []
    for (w, d), p in sorted(pick.items()):
        if (w, d) in drop:
            out.append({"driver_id": d, "other_driver": d, "pickups": p,
                        "dropoffs": drop[(w, d)]})
        else:
            out.append({"driver_id": d, "other_driver": None, "pickups": p,
                        "dropoffs": None})
    for (w, d), dr in sorted(drop.items()):
        if (w, d) not in pick:
            out.append({"driver_id": None, "other_driver": d, "pickups": None,
                        "dropoffs": dr})
    return out


def o_updating_aggregate(ins):
    byg = defaultdict(list)
    for r in ins["impulse"]:
        byg[r["counter"] % 7].append(r["counter"])
    return [
        {"g": g, "c": len(cs), "total": sum(cs)} for g, cs in sorted(byg.items())
    ]


def o_spill_keyspace(ins):
    byu = defaultdict(list)
    for r in ins["spill_users"]:
        byu[r["user_id"]].append(r["amount"])
    return [
        {"u": u, "c": len(a), "total": sum(a)} for u, a in sorted(byu.items())
    ]


def o_filter_updating_aggregates(ins):
    byg = defaultdict(int)
    for r in ins["impulse"]:
        byg[r["counter"] % 7] += 1
    return [{"g": g, "c": c} for g, c in sorted(byg.items()) if c % 2 == 0]


def o_updating_inner_join(ins):
    names = {c["customer_id"]: c["name"] for c in ins["customers"]}
    out = []
    for o in ins["orders"]:
        if o["customer_id"] in names:
            out.append({
                "order_id": o["order_id"], "customer_id": o["customer_id"],
                "name": names[o["customer_id"]], "amount": o["amount"],
            })
    return out


def o_updating_left_join(ins):
    orders_by_cust = defaultdict(list)
    for o in ins["orders"]:
        orders_by_cust[o["customer_id"]].append(o["order_id"])
    out = []
    for c in ins["customers"]:
        oids = orders_by_cust.get(c["customer_id"])
        if oids:
            for oid in oids:
                out.append({"customer_id": c["customer_id"], "name": c["name"],
                            "order_id": oid})
        else:
            out.append({"customer_id": c["customer_id"], "name": c["name"],
                        "order_id": None})
    return out


def _final_right_sub(ins):
    """Final state of the updating subquery: count(*) per counter%2 over
    impulse counters < 3 -> [(counter_mod_2, right_count)]."""
    byg = defaultdict(int)
    for r in ins["impulse"]:
        if r["counter"] < 3:
            byg[r["counter"] % 2] += 1
    return sorted(byg.items())


def o_updating_right_join(ins):
    """impulse RIGHT JOIN updating-agg subquery ON counter = right_count
    WHERE counter < 3 (reference rejects updating right sides; we run it).
    The WHERE on the nullable left column drops null-padded rows."""
    counters = {r["counter"] for r in ins["impulse"]}
    out = []
    for cm2, rc in _final_right_sub(ins):
        if rc in counters and rc < 3:
            out.append({"left_counter": rc, "counter_mod_2": cm2, "right_count": rc})
    return out


def o_updating_full_join(ins):
    """(impulse counters < 5) FULL JOIN updating-agg subquery ON
    counter = right_count: matches plus null-padded rows from BOTH sides."""
    left = sorted({r["counter"] for r in ins["impulse"] if r["counter"] < 5})
    sub = _final_right_sub(ins)
    matched_rc = set()
    out = []
    for cm2, rc in sub:
        if rc in left:
            out.append({"left_counter": rc, "counter_mod_2": cm2, "right_count": rc})
            matched_rc.add(rc)
    for c in left:
        if c not in matched_rc:
            out.append({"left_counter": c, "counter_mod_2": None, "right_count": None})
    for cm2, rc in sub:
        if rc not in left:
            out.append({"left_counter": None, "counter_mod_2": cm2, "right_count": rc})
    return out


def o_updating_inner_join_with_updating(ins):
    counters = {r["counter"] for r in ins["impulse"]}
    return [
        {"left_counter": rc, "counter_mod_2": cm2, "right_count": rc}
        for cm2, rc in _final_right_sub(ins)
        if rc in counters and rc < 3
    ]


def o_debezium_pass_through(ins):
    _envs, final = gen_aggregate_updates()
    return [
        {"id": r["id"], "customer_name": r["customer_name"],
         "product_name": r["product_name"], "quantity": r["quantity"],
         "price": r["price"], "status": r["status"]}
        for r in final.values()
    ]


def o_debezium_coercion(ins):
    return [{"counter": r["counter"]} for r in ins["impulse"]]


def o_debezium_agg(ins):
    _envs, final = gen_aggregate_updates()
    byp = defaultdict(lambda: [0, set(), 0])
    for r in final.values():
        acc = byp[f"p_{r['product_name']}"]
        acc[0] += 1
        acc[1].add(r["customer_name"])
        acc[2] += r["quantity"] + 5
    return [{"p": p, "c": c, "d": len(d), "q": q + 10}
            for p, (c, d, q) in sorted(byp.items())]


def o_json_operators(ins):
    return [
        {"a": "test", "b": json.dumps(r["driver_id"]),
         "c": json.dumps(r["event_type"]), "d": "null"}
        for r in ins["cars"]
    ]


def o_unnest_in_view(ins):
    return [{"counter": r["counter"]} for r in ins["impulse"]]


def o_offset_impulse_join(ins):
    W = 1 * S
    out = []
    for r in ins["impulse"]:
        ts = input_ts(r, "timestamp")
        out.append({"start": iso(tumble_start(ts, W)), "counter": r["counter"]})
    return out


def o_async_udf(ins):
    return [{"counter": -2 * r["counter"]} for r in ins["impulse"]]


def o_most_active_driver(ins):
    SLIDE, W = 20 * S, 60 * S
    byw = defaultdict(lambda: defaultdict(int))
    for r in ins["cars"]:
        ts = input_ts(r, "timestamp")
        sb = (ts // SLIDE) * SLIDE
        for k in range(W // SLIDE):
            start = sb - k * SLIDE
            byw[start][r["driver_id"]] += 1
    out = []
    for w, drivers in sorted(byw.items()):
        # ORDER BY c DESC, driver_id DESC, take row 1
        d, c = max(drivers.items(), key=lambda kv: (kv[1], kv[0]))
        out.append({"start": iso(w), "driver_id": d, "cnt": c, "rn": 1})
    return out


def o_count_distinct(ins):
    W = 20 * S
    groups = defaultdict(lambda: (set(), 0))
    for r in ins["cars"]:
        w = tumble_start(input_ts(r, "timestamp"), W)
        drivers, n = groups[(w, r["event_type"])]
        drivers.add(r["driver_id"])
        groups[(w, r["event_type"])] = (drivers, n + 1)
    return [
        {"start": iso(w), "et": et, "drivers": len(d), "events": n}
        for (w, et), (d, n) in sorted(groups.items())
    ]


def o_memory_table(ins):
    return [{"driver_id": r["driver_id"], "event_type": r["event_type"]}
            for r in ins["cars"]]


def o_window_function(ins):
    W = 10 * S
    byk = defaultdict(int)
    for r in ins["bids"]:
        byk[(tumble_start(input_ts(r, "datetime"), W), r["auction"])] += 1
    byw = defaultdict(list)
    for (w, a), n in byk.items():
        byw[w].append((a, n))
    out = []
    for w, pairs in sorted(byw.items()):
        ranked = sorted(pairs, key=lambda p: (-p[1], p[0]))
        for i, (a, n) in enumerate(ranked[:2]):
            out.append({"start": iso(w), "auction": a, "bids": n, "row_num": i + 1})
    return out


def o_union_all(ins):
    out = []
    for r in ins["cars"]:
        if r["event_type"] == "pickup":
            out.append({"driver_id": r["driver_id"], "tag": "pick"})
    for r in ins["cars"]:
        if r["event_type"] == "dropoff":
            out.append({"driver_id": r["driver_id"], "tag": "drop"})
    return out


def o_having_filter(ins):
    W = 10 * S
    byk = defaultdict(list)
    for r in ins["bids"]:
        byk[(tumble_start(input_ts(r, "datetime"), W), r["auction"])].append(r["price"])
    return [
        {"start": iso(w), "auction": a, "bids": len(ps),
         "avg_price": sum(ps) / len(ps)}
        for (w, a), ps in sorted(byk.items())
        if len(ps) > 18
    ]


def o_nexmark_q1(ins):
    return [
        {"auction": r["auction"], "price_eur": r["price"] * 89 // 100,
         "bidder": r["bidder"]}
        for r in ins["bids"]
    ]


def o_nexmark_q2(ins):
    return [
        {"auction": r["auction"], "price": r["price"]}
        for r in ins["bids"]
        if r["auction"] in (1000, 1200, 1400)
    ]


def o_nexmark_q7(ins):
    W = 10 * S
    per = defaultdict(int)
    glob = defaultdict(int)
    for r in ins["bids"]:
        w = tumble_start(input_ts(r, "datetime"), W)
        per[(w, r["auction"])] = max(per[(w, r["auction"])], r["price"])
        glob[w] = max(glob[w], r["price"])
    return [
        {"auction": a, "price": p}
        for (w, a), p in sorted(per.items())
        if p == glob[w]
    ]


def o_every_aggregate(ins):
    W = 20 * S
    byw = defaultdict(list)
    for r in ins["orders"]:
        byw[tumble_start(input_ts(r, "timestamp"), W)].append(r["amount"])
    return [
        {"start": iso(w), "n": len(a), "total": sum(a), "lo": min(a),
         "hi": max(a), "mean": sum(a) / len(a),
         "dbl_total": sum(x * 2 for x in a),
         "shifted_lo": min(a) + 100}
        for w, a in sorted(byw.items())
    ]


def o_session_udaf(ins):
    gap = 5 * S
    byc = defaultdict(list)
    for r in ins["orders"]:
        byc[r["customer_id"]].append((input_ts(r, "timestamp"), r["amount"]))
    out = []
    for c, rows in sorted(byc.items()):
        rows.sort()
        # split into sessions by gap, mirroring sessions()
        cur: list = []
        groups = []
        last = None
        for t, amt in rows:
            if last is not None and t - last > gap:
                groups.append(cur)
                cur = []
            cur.append((t, amt))
            last = t
        if cur:
            groups.append(cur)
        for g in groups:
            amts = [a for _t, a in g]
            # p90 mirrors numpy.percentile(linear interpolation)
            import numpy as _np

            out.append({
                "start": iso(g[0][0]), "customer_id": c, "n": len(g),
                "p90_amount": float(_np.percentile(_np.array(amts, dtype=float), 90)),
                "spread": max(amts) - min(amts),
            })
    return out


def o_windowed_left_join(ins):
    W = 20 * S
    pick = defaultdict(int)
    drop = defaultdict(int)
    for r in ins["cars"]:
        k = (tumble_start(input_ts(r, "timestamp"), W), r["driver_id"])
        if r["event_type"] == "pickup":
            pick[k] += 1
        if r["event_type"] == "dropoff" and r["driver_id"] % 3 == 0:
            drop[k] += 1
    return [
        {"driver_id": d, "pickups": p, "dropoffs": drop.get((w, d))}
        for (w, d), p in sorted(pick.items())
    ]


def o_string_keys(ins):
    W = 20 * S
    byk = defaultdict(int)
    for r in ins["cars"]:
        byk[(tumble_start(input_ts(r, "timestamp"), W), r["location"], r["event_type"])] += 1
    return [
        {"start": iso(w), "location": loc, "event_type": et, "events": n}
        for (w, loc, et), n in sorted(byk.items())
    ]


def o_nested_subquery(ins):
    W = 10 * S
    byk = defaultdict(int)
    for r in ins["cars"]:
        byk[(tumble_start(input_ts(r, "timestamp"), W), r["driver_id"])] += 1
    byw = defaultdict(list)
    for (w, _d), n in byk.items():
        byw[w].append(n)
    return [
        {"busiest_driver_events": max(ns), "drivers": len(ns)}
        for w, ns in sorted(byw.items())
    ]


def o_cast_to_sink_type(ins):
    return [
        {"counter_text": str(r["counter"]),
         "counter_float": float(r["counter"]),
         "counter_small": r["counter"]}
        for r in ins["impulse"]
    ]


def o_null_comparisons(ins):
    out = []
    for r in ins["impulse"]:
        c = r["counter"]
        if c < 5:
            out.append({"counter": c, "small": c, "is_gt": c > 2})
        else:
            # no right-side match: padding is NULL and the projected
            # comparison propagates NULL (three-valued logic), not False
            out.append({"counter": c, "small": None, "is_gt": None})
    return out


ORACLES = {
    "select_star": o_select_star,
    "nexmark_q1": o_nexmark_q1,
    "nexmark_q2": o_nexmark_q2,
    "nexmark_q7": o_nexmark_q7,
    "every_aggregate": o_every_aggregate,
    "session_udaf": o_session_udaf,
    "windowed_left_join": o_windowed_left_join,
    "string_keys": o_string_keys,
    "nested_subquery": o_nested_subquery,
    "expressions": o_expressions,
    "tumbling_aggregates": o_tumbling_aggregates,
    "grouped_aggregates": o_grouped_aggregates,
    "sliding_window": o_sliding_window,
    "session_window": o_session_window,
    "nexmark_q5": o_nexmark_q5,
    "windowed_inner_join": o_windowed_inner_join,
    "windowed_full_join": o_windowed_full_join,
    "updating_aggregate": o_updating_aggregate,
    "spill_keyspace": o_spill_keyspace,
    "filter_updating_aggregates": o_filter_updating_aggregates,
    "updating_inner_join": o_updating_inner_join,
    "updating_left_join": o_updating_left_join,
    "updating_right_join": o_updating_right_join,
    "updating_full_join": o_updating_full_join,
    "updating_inner_join_with_updating": o_updating_inner_join_with_updating,
    "async_udf": o_async_udf,
    "memory_table": o_memory_table,
    "count_distinct": o_count_distinct,
    "most_active_driver": o_most_active_driver,
    "offset_impulse_join": o_offset_impulse_join,
    "unnest_in_view": o_unnest_in_view,
    "json_operators": o_json_operators,
    "debezium_pass_through": o_debezium_pass_through,
    "debezium_coercion": o_debezium_coercion,
    "debezium_agg": o_debezium_agg,
    "window_function": o_window_function,
    "union_all": o_union_all,
    "having_filter": o_having_filter,
    "cast_to_sink_type": o_cast_to_sink_type,
    "null_comparisons": o_null_comparisons,
}

# queries whose sinks receive an updating stream (harness debezium-merges
# engine output before diffing; goldens hold the final merged rows)
UPDATING = {
    "updating_aggregate",
    "spill_keyspace",
    "filter_updating_aggregates",
    "updating_inner_join",
    "updating_left_join",
    "updating_right_join",
    "updating_full_join",
    "updating_inner_join_with_updating",
    "debezium_pass_through",
    "debezium_agg",
    "null_comparisons",
}


def main():
    os.makedirs(INPUTS, exist_ok=True)
    os.makedirs(GOLDEN, exist_ok=True)
    ins = {
        "impulse": gen_impulse(),
        "cars": gen_cars(),
        "bids": gen_bids(),
        "orders": gen_orders(),
        "customers": gen_customers(),
        "spill_users": gen_spill_users(),
    }
    for name, rows in ins.items():
        with open(os.path.join(INPUTS, f"{name}.json"), "w") as f:
            for r in rows:
                f.write(json.dumps(r, separators=(",", ":")) + "\n")
        print(f"inputs/{name}.json: {len(rows)} rows")
    envs, _final = gen_aggregate_updates()
    with open(os.path.join(INPUTS, "aggregate_updates.json"), "w") as f:
        for e in envs:
            f.write(json.dumps(e, separators=(",", ":")) + "\n")
    print(f"inputs/aggregate_updates.json: {len(envs)} envelopes")
    for qname, oracle in ORACLES.items():
        rows = oracle(ins)
        with open(os.path.join(GOLDEN, f"{qname}.json"), "w") as f:
            for r in rows:
                f.write(json.dumps(r, separators=(",", ":")) + "\n")
        print(f"golden/{qname}.json: {len(rows)} rows")


if __name__ == "__main__":
    sys.exit(main())
