-- warn: AR006
-- The memory-table branch is written but never read: its source still
-- gates watermarks and barriers for the whole pipeline.
CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE mem (driver_id BIGINT);
CREATE TABLE output (
  counter BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO mem SELECT driver_id FROM cars;
INSERT INTO output SELECT counter FROM impulse_source;
