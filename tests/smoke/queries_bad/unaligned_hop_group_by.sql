-- reject: AR002
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE output (
  start TIMESTAMP, c BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO output
SELECT x.w.start, x.c FROM (
  SELECT hop(interval '3 seconds', interval '10 seconds') AS w, count(*) AS c
  FROM impulse_source GROUP BY 1
) x;
