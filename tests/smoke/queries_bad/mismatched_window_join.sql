-- reject: AR000
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE output (
  c1 BIGINT, c2 BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO output
SELECT a.c, b.c FROM (
  SELECT tumble(interval '10 seconds') AS w, count(*) AS c
  FROM impulse_source GROUP BY 1
) a JOIN (
  SELECT tumble(interval '20 seconds') AS w, count(*) AS c
  FROM impulse_source GROUP BY 1
) b ON a.w = b.w;
