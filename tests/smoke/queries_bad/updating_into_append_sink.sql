-- warn: AR005
-- Updating aggregate into a plain-json sink: rows arrive wrapped in
-- Debezium envelopes the declared schema does not describe.
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE output (
  g BIGINT, c BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO output
SELECT CAST(counter % 3 AS BIGINT) AS g, count(*) AS c
FROM impulse_source GROUP BY 1;
