-- reject: AR002
-- The reference's --fail test: hop() whose slide does not divide the
-- width must be rejected at plan time, not blow up at runtime.
CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE output (
  start TIMESTAMP, driver_id BIGINT, cnt BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO output
SELECT x.w.start, x.driver_id, x.c FROM (
  SELECT hop(interval '25 seconds', interval '60 seconds') AS w,
         driver_id, count(*) AS c
  FROM cars GROUP BY 1, 2
) x;
