-- reject: AR000
-- min() needs an invertible accumulator to survive retractions.
CREATE TABLE orders_cdc (
  id INT,
  customer_name TEXT,
  product_name TEXT,
  quantity INT,
  price DOUBLE,
  status TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/aggregate_updates.json',
  format = 'debezium_json',
  type = 'source'
);
CREATE TABLE output (
  p TEXT, m BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO output
SELECT product_name, min(quantity) FROM orders_cdc GROUP BY product_name;
