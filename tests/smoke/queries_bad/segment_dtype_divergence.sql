-- reject: AR009
-- Dual-path dtype divergence in a compile-marked segment: BIGINT * REAL
-- computes float64 on the interpreted (numpy) path but float32 under the
-- traced (jax x64) path — the one corner where the jax promotion lattice
-- departs from numpy. The byte-exactness contract cannot hold, so AR009
-- rejects the pipeline at plan time instead of letting the first-batch
-- verification discover the divergence per (segment, schema) at runtime.
CREATE TABLE src (
  a BIGINT NOT NULL,
  b REAL NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source'
);

CREATE TABLE sink (
  x DOUBLE
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);

INSERT INTO sink
SELECT a * b
FROM src;
