-- reject: AR008
-- The bad_state test connector (tests/smoke/udfs.py) declares TWO state
-- tables named 's': the checkpoint path scheme keys files by
-- (operator, table, subtask), so the tables would overwrite each other's
-- snapshots and restore would resurrect only one. The plan analyzer
-- instantiates each node's operator and rejects the collision before any
-- state is allocated.
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'bad_state',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE output (
  counter BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO output SELECT counter FROM impulse_source;
