-- reject: AR000
-- A retracting (debezium) stream cannot feed an event-time window.
CREATE TABLE orders_cdc (
  id INT,
  customer_name TEXT,
  product_name TEXT,
  quantity INT,
  price DOUBLE,
  status TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/aggregate_updates.json',
  format = 'debezium_json',
  type = 'source'
);
CREATE TABLE output (
  start TIMESTAMP, c BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO output
SELECT x.w.start, x.c FROM (
  SELECT tumble(interval '10 seconds') AS w, count(*) AS c
  FROM orders_cdc GROUP BY 1
) x;
