-- warn: AR004
-- Non-windowed join over unbounded kafka sources with no TTL: both
-- join-side state tables grow forever.
CREATE TABLE orders (
  order_id BIGINT, customer_id BIGINT, amount BIGINT
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  topic = 'orders',
  format = 'json',
  type = 'source'
);
CREATE TABLE customers (
  customer_id BIGINT, name TEXT
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  topic = 'customers',
  format = 'json',
  type = 'source'
);
CREATE TABLE output (
  order_id BIGINT, name TEXT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO output
SELECT o.order_id, c.name FROM orders o
JOIN customers c ON o.customer_id = c.customer_id;
