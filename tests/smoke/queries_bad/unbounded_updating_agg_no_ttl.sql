-- warn: AR004
CREATE TABLE events (
  user_id BIGINT, kind TEXT
) WITH (
  connector = 'kafka',
  bootstrap_servers = 'localhost:9092',
  topic = 'events',
  format = 'json',
  type = 'source'
);
CREATE TABLE output (
  user_id BIGINT, c BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO output SELECT user_id, count(*) FROM events GROUP BY user_id;
