"""Test UDF/UDAF fixtures for the smoke suite (the reference registers its
test UDFs from fixture sources the same way, arroyo-planner test/udfs/).
Importing this module registers them; generate.py mirrors the math in its
oracles."""

import time

import numpy as np

from arroyo_tpu.udf import register_udaf, register_udf


def p90(values: np.ndarray) -> float:
    return float(np.percentile(np.asarray(values, dtype=np.float64), 90))


def val_range(values: np.ndarray) -> int:
    v = np.asarray(values)
    return int(v.max() - v.min())


def double_negative(counter) -> int:
    """Async scalar UDF (reference double_negative_udf.sql: an async Rust
    udf over impulse.counter); the sleep forces real overlap through the
    bounded-concurrency pool."""
    time.sleep(0.0002)
    return -2 * int(counter)


register_udaf("p90", p90, return_dtype="float64")
register_udaf("val_range", val_range, return_dtype="int64")
register_udf("double_negative", double_negative, return_dtype="int64",
             is_async=True, max_concurrency=16, ordered=True)


# --- AR008 fixture connector ------------------------------------------------
# A deliberately mis-declared source: two state tables sharing one name.
# This is the operator-author bug class AR008 (table-spec-consistency)
# rejects at plan time; queries_bad/duplicate_table_specs.sql drives it.
from arroyo_tpu.connectors import register_source
from arroyo_tpu.connectors.single_file import SingleFileSource
from arroyo_tpu.operators.base import TableSpec


class BadStateSource(SingleFileSource):
    def tables(self):
        return [TableSpec("s", "global_keyed"),
                TableSpec("s", "expiring_time_key")]


register_source("bad_state")(BadStateSource)
