"""Test UDF/UDAF fixtures for the smoke suite (the reference registers its
test UDFs from fixture sources the same way, arroyo-planner test/udfs/).
Importing this module registers them; generate.py mirrors the math in its
oracles."""

import numpy as np

from arroyo_tpu.udf import register_udaf


def p90(values: np.ndarray) -> float:
    return float(np.percentile(np.asarray(values, dtype=np.float64), 90))


def val_range(values: np.ndarray) -> int:
    v = np.asarray(values)
    return int(v.max() - v.min())


register_udaf("p90", p90, return_dtype="float64")
register_udaf("val_range", val_range, return_dtype="int64")
