CREATE TABLE bids (
  datetime TIMESTAMP,
  auction BIGINT,
  price BIGINT,
  bidder TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/bids.json',
  format = 'json',
  type = 'source',
  event_time_field = 'datetime'
);
CREATE TABLE selected (
  auction BIGINT,
  price BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO selected
SELECT auction, price FROM bids
WHERE auction = 1000 OR auction = 1200 OR auction = 1400;
