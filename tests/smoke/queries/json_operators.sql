-- JSON access operators over an unstructured source: -> yields the value
-- re-serialized as JSON text; missing fields yield "null" (reference
-- json_operators.sql + golden_outputs/json_operators.json).
CREATE TABLE cars (
  value JSON
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  'json.unstructured' = 'true'
);

CREATE TABLE sink (
  a TEXT,
  b TEXT,
  c TEXT,
  d TEXT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);

INSERT INTO sink
SELECT 'test' AS a, value->'driver_id' AS b, value->'event_type' AS c,
       value->'not_a_field' AS d
FROM cars;
