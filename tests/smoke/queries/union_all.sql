CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE union_output (
  driver_id BIGINT,
  tag TEXT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO union_output
SELECT driver_id, 'pick' AS tag FROM cars WHERE event_type = 'pickup'
UNION ALL
SELECT driver_id, 'drop' AS tag FROM cars WHERE event_type = 'dropoff';
