CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE expr_output (
  c BIGINT,
  doubled BIGINT,
  parity TEXT,
  clamped DOUBLE,
  label TEXT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO expr_output
SELECT
  CAST(counter AS BIGINT) AS c,
  CAST(counter * 2 AS BIGINT) AS doubled,
  CASE WHEN counter % 2 = 0 THEN 'even' ELSE 'odd' END AS parity,
  sqrt(CAST(counter AS DOUBLE)) AS clamped,
  concat('row_', CAST(counter AS TEXT)) AS label
FROM impulse_source
WHERE counter >= 10 AND counter < 60 AND NOT (counter BETWEEN 30 AND 39);
