CREATE TABLE orders (
  timestamp TIMESTAMP,
  order_id BIGINT,
  customer_id BIGINT,
  amount BIGINT
) WITH (
  connector = 'single_file',
  path = '$input_dir/orders.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE customers (
  timestamp TIMESTAMP,
  customer_id BIGINT,
  name TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/customers.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE join_output (
  customer_id BIGINT,
  name TEXT,
  order_id BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO join_output
SELECT c.customer_id, c.name, o.order_id
FROM customers c
LEFT JOIN orders o ON c.customer_id = o.customer_id;
