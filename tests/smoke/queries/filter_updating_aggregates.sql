CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE filtered_output (
  g BIGINT,
  c BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO filtered_output
SELECT g, c FROM (
  SELECT CAST(counter % 7 AS BIGINT) AS g, count(*) AS c
  FROM impulse_source
  GROUP BY counter % 7
) x
WHERE c % 2 = 0;
