CREATE TABLE bids (
  datetime TIMESTAMP,
  auction BIGINT,
  price BIGINT,
  bidder TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/bids.json',
  format = 'json',
  type = 'source',
  event_time_field = 'datetime'
);
CREATE TABLE hot_output (
  start TIMESTAMP,
  auction BIGINT,
  bids BIGINT,
  avg_price DOUBLE
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO hot_output
SELECT window.start AS start, auction, bids, avg_price FROM (
  SELECT tumble(interval '10 seconds') AS window, auction,
    count(*) AS bids, avg(CAST(price AS DOUBLE)) AS avg_price
  FROM bids
  GROUP BY window, auction
  HAVING count(*) > 18
) x;
