-- Windowed join of two readings of the same source where one side's
-- watermark lags by 10 minutes (WATERMARK FOR ... AS expr DDL); the join
-- must still line windows up (reference offset_impulse_join.sql).
CREATE TABLE impulse_source (
  timestamp TIMESTAMP NOT NULL,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL,
  WATERMARK FOR timestamp
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);

CREATE TABLE delayed_impulse_source (
  timestamp TIMESTAMP NOT NULL,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL,
  WATERMARK FOR timestamp AS (timestamp - INTERVAL '10 minute')
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);

CREATE TABLE offset_output (
  start TIMESTAMP,
  counter BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);

INSERT INTO offset_output
SELECT a.window.start, a.counter AS counter
FROM (
  SELECT tumble(interval '1 second') AS window, counter, count(*) AS c
  FROM impulse_source GROUP BY window, counter
) a
JOIN (
  SELECT tumble(interval '1 second') AS window, counter, count(*) AS c
  FROM delayed_impulse_source GROUP BY window, counter
) b
ON a.counter = b.counter AND a.window = b.window;
