CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE session_output (
  start TIMESTAMP,
  "end" TIMESTAMP,
  user_id BIGINT,
  rows BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO session_output
SELECT window.start AS start, window.end AS "end", user_id, rows FROM (
  SELECT session(interval '20 seconds') AS window,
    CAST(CASE WHEN counter % 10 = 0 THEN 0 ELSE counter END AS BIGINT) AS user_id,
    count(*) AS rows
  FROM impulse_source
  GROUP BY window, user_id
) x;
