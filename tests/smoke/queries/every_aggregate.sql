CREATE TABLE orders (
  timestamp TIMESTAMP,
  order_id BIGINT,
  customer_id BIGINT,
  amount BIGINT
) WITH (
  connector = 'single_file',
  path = '$input_dir/orders.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE agg_out (
  start TIMESTAMP,
  n BIGINT,
  total BIGINT,
  lo BIGINT,
  hi BIGINT,
  mean DOUBLE,
  dbl_total BIGINT,
  shifted_lo BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO agg_out
SELECT window.start AS start, n, total, lo, hi, mean, dbl_total, shifted_lo FROM (
  SELECT tumble(interval '20 seconds') AS window,
    count(*) AS n,
    sum(amount) AS total,
    min(amount) AS lo,
    max(amount) AS hi,
    avg(amount) AS mean,
    sum(amount * 2) AS dbl_total,
    min(amount + 100) AS shifted_lo
  FROM orders
  GROUP BY window
) x;
