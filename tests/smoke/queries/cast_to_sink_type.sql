-- Sink-type coercion end-to-end: the query's BIGINT UNSIGNED output is
-- positionally cast to each declared sink column type (TEXT / DOUBLE / INT)
-- by the planner's sink_coerce projection; reference cast_to_sink_type.sql.
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);

CREATE TABLE cast_output (
  counter_text TEXT,
  counter_float DOUBLE,
  counter_small INT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);

INSERT INTO cast_output
SELECT counter, counter, counter
FROM impulse_source;
