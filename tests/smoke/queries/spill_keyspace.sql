-- Tiered-state smoke family (ISSUE 14): an updating aggregate over a
-- keyspace ~10x a small state.spill.budget-bytes. tests/test_spill.py runs
-- this family with spilling enabled (tiny budget, chaos axes included) and
-- asserts byte-exact goldens with spill actively engaged; the default
-- (spill-off) smoke/segment sweeps prove the resident path on the same
-- golden.
CREATE TABLE spill_users (
  timestamp TIMESTAMP,
  user_id BIGINT NOT NULL,
  amount BIGINT NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/spill_users.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE spill_output (
  u BIGINT,
  c BIGINT,
  total BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO spill_output
SELECT user_id AS u, count(*) AS c, CAST(sum(amount) AS BIGINT) AS total
FROM spill_users
GROUP BY user_id;
