CREATE TABLE bids (
  datetime TIMESTAMP,
  auction BIGINT,
  price BIGINT,
  bidder TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/bids.json',
  format = 'json',
  type = 'source',
  event_time_field = 'datetime'
);
CREATE TABLE top2_output (
  start TIMESTAMP,
  auction BIGINT,
  bids BIGINT,
  row_num BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO top2_output
SELECT start, auction, bids, row_num FROM (
  SELECT window.start AS start, auction, bids,
    ROW_NUMBER() OVER (PARTITION BY window ORDER BY bids DESC, auction ASC) AS row_num
  FROM (
    SELECT tumble(interval '10 seconds') AS window, auction, count(*) AS bids
    FROM bids
    GROUP BY window, auction
  ) counts
) ranked
WHERE row_num <= 2;
