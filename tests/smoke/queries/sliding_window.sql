CREATE TABLE bids (
  datetime TIMESTAMP,
  auction BIGINT,
  price BIGINT,
  bidder TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/bids.json',
  format = 'json',
  type = 'source',
  event_time_field = 'datetime'
);
CREATE TABLE slide_output (
  start TIMESTAMP,
  "end" TIMESTAMP,
  auction BIGINT,
  bids BIGINT,
  top_price BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO slide_output
SELECT window.start AS start, window.end AS "end", auction, bids, top_price FROM (
  SELECT hop(interval '2 seconds', interval '10 seconds') AS window,
    auction, count(*) AS bids, CAST(max(price) AS BIGINT) AS top_price
  FROM bids
  GROUP BY window, auction
) x;
