CREATE TABLE orders (
  timestamp TIMESTAMP,
  order_id BIGINT,
  customer_id BIGINT,
  amount BIGINT
) WITH (
  connector = 'single_file',
  path = '$input_dir/orders.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE customers (
  timestamp TIMESTAMP,
  customer_id BIGINT,
  name TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/customers.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE join_output (
  order_id BIGINT,
  customer_id BIGINT,
  name TEXT,
  amount BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO join_output
SELECT o.order_id, o.customer_id, c.name, o.amount
FROM orders o
JOIN customers c ON o.customer_id = c.customer_id;
