CREATE TABLE bids (
  datetime TIMESTAMP,
  auction BIGINT,
  price BIGINT,
  bidder TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/bids.json',
  format = 'json',
  type = 'source',
  event_time_field = 'datetime'
);
CREATE TABLE converted (
  auction BIGINT,
  price_eur BIGINT,
  bidder TEXT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO converted
SELECT auction, price * 89 / 100 AS price_eur, bidder FROM bids;
