-- Debezium SOURCE -> debezium sink pass-through: c/u/d envelopes flow in
-- as retract-tagged rows and out as envelopes again (reference
-- debezium_pass_through.sql; de.rs debezium handling).
CREATE TABLE debezium_source (
  id INT PRIMARY KEY,
  customer_name TEXT,
  product_name TEXT,
  quantity INTEGER,
  price FLOAT,
  status TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/aggregate_updates.json',
  format = 'debezium_json',
  type = 'source'
);

CREATE TABLE output (
  id INT PRIMARY KEY,
  customer_name TEXT,
  product_name TEXT,
  quantity INTEGER,
  price FLOAT,
  status TEXT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);

INSERT INTO output
SELECT *
FROM debezium_source;
