CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE agg_output (
  start TIMESTAMP,
  "end" TIMESTAMP,
  rows BIGINT,
  total BIGINT,
  min_c BIGINT,
  max_c BIGINT,
  avg_c DOUBLE
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO agg_output
SELECT window.start AS start, window.end AS "end", rows, total, min_c, max_c, avg_c FROM (
  SELECT tumble(interval '10 seconds') AS window,
    count(*) AS rows,
    CAST(sum(counter) AS BIGINT) AS total,
    CAST(min(counter) AS BIGINT) AS min_c,
    CAST(max(counter) AS BIGINT) AS max_c,
    avg(CAST(counter AS DOUBLE)) AS avg_c
  FROM impulse_source
  GROUP BY window
) x;
