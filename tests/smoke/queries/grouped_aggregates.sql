CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE grouped_output (
  start TIMESTAMP,
  g BIGINT,
  rows BIGINT,
  total BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO grouped_output
SELECT window.start AS start, g, rows, total FROM (
  SELECT tumble(interval '10 seconds') AS window,
    CAST(counter % 3 AS BIGINT) AS g,
    count(*) AS rows,
    CAST(sum(counter) AS BIGINT) AS total
  FROM impulse_source
  GROUP BY window, g
) x;
