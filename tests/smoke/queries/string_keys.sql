CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE by_location (
  start TIMESTAMP,
  location TEXT,
  event_type TEXT,
  events BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO by_location
SELECT window.start AS start, location, event_type, events FROM (
  SELECT tumble(interval '20 seconds') AS window, location, event_type,
    count(*) AS events
  FROM cars
  GROUP BY window, location, event_type
) x;
