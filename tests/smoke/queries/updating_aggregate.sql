CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE updating_output (
  g BIGINT,
  c BIGINT,
  total BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO updating_output
SELECT CAST(counter % 7 AS BIGINT) AS g, count(*) AS c,
  CAST(sum(counter) AS BIGINT) AS total
FROM impulse_source
GROUP BY counter % 7;
