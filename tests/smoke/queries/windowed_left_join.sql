CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE join_output (
  driver_id BIGINT,
  pickups BIGINT,
  dropoffs BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO join_output
SELECT p.driver_id, p.pickups, d.dropoffs
FROM (
  SELECT tumble(interval '20 seconds') AS window, driver_id, count(*) AS pickups
  FROM cars WHERE event_type = 'pickup'
  GROUP BY window, driver_id
) p
LEFT JOIN (
  SELECT tumble(interval '20 seconds') AS window, driver_id, count(*) AS dropoffs
  FROM cars WHERE event_type = 'dropoff' AND driver_id % 3 = 0
  GROUP BY window, driver_id
) d
ON p.driver_id = d.driver_id AND p.window = d.window;
