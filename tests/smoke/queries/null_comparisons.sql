-- Three-valued logic through a projection: comparisons over outer-join
-- null padding must project NULL (not False); only counters < 5 have a
-- right-side match, so rows >= 5 sink is_gt = NULL and rows 0..2 / 3..4
-- exercise the False / True legs of the same comparison.
CREATE TABLE impulse_source (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);

CREATE TABLE null_output (
  counter BIGINT,
  small BIGINT,
  is_gt BOOLEAN
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);

INSERT INTO null_output
SELECT i.counter, r.c2, r.c2 > 2 AS is_gt
FROM impulse_source i
LEFT JOIN (
  SELECT counter AS c2 FROM impulse_source WHERE counter < 5
) r ON i.counter = r.c2;
