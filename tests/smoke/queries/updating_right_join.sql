-- RIGHT JOIN of an append stream against an UPDATING aggregate subquery.
-- The reference REJECTS this ("can't handle updating right side of join",
-- updating_right_join.sql --fail marker); JoinWithExpiration's symmetric
-- retract handling supports it, so here it is a positive test.
CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE output (
  left_counter BIGINT,
  counter_mod_2 BIGINT,
  right_count BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO output
SELECT i.counter AS left_counter, sub.counter_mod_2, sub.right_count
FROM impulse i
RIGHT JOIN (
  SELECT CAST(counter % 2 AS BIGINT) AS counter_mod_2,
         count(*) AS right_count
  FROM impulse WHERE counter < 3 GROUP BY counter % 2
) sub
ON i.counter = sub.right_count
WHERE i.counter < 3;
