CREATE TABLE orders (
  timestamp TIMESTAMP,
  order_id BIGINT,
  customer_id BIGINT,
  amount BIGINT
) WITH (
  connector = 'single_file',
  path = '$input_dir/orders.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE spend_sessions (
  start TIMESTAMP,
  customer_id BIGINT,
  n BIGINT,
  p90_amount DOUBLE,
  spread BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO spend_sessions
SELECT window.start AS start, customer_id, n, p90_amount, spread FROM (
  SELECT session(interval '5 seconds') AS window, customer_id,
    count(*) AS n,
    p90(amount) AS p90_amount,
    val_range(amount) AS spread
  FROM orders
  GROUP BY window, customer_id
) x;
