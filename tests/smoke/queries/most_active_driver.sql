-- Hop-window aggregate ranked by ROW_NUMBER() OVER and filtered to the
-- top row per window (reference most_active_driver_last_hour.sql).
CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE most_active_driver (
  start TIMESTAMP,
  driver_id BIGINT,
  cnt BIGINT,
  rn BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO most_active_driver
SELECT y.w.start, y.driver_id, y.c, y.rn FROM (
  SELECT x.w, x.driver_id, x.c, ROW_NUMBER() OVER (
    PARTITION BY x.w ORDER BY x.c DESC, x.driver_id DESC) AS rn
  FROM (
    SELECT hop(interval '20 seconds', interval '60 seconds') AS w,
           driver_id, count(*) AS c
    FROM cars GROUP BY 1, 2
  ) x
) y WHERE y.rn = 1;
