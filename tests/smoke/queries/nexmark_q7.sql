CREATE TABLE bids (
  datetime TIMESTAMP,
  auction BIGINT,
  price BIGINT,
  bidder TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/bids.json',
  format = 'json',
  type = 'source',
  event_time_field = 'datetime'
);
CREATE TABLE highest_bids (
  auction BIGINT,
  price BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO highest_bids
SELECT PerAuction.auction, PerAuction.mx
FROM (
  SELECT auction, max(price) AS mx,
    tumble(interval '10 seconds') AS window
  FROM bids GROUP BY auction, window
) AS PerAuction
JOIN (
  SELECT max(price) AS mx,
    tumble(interval '10 seconds') AS window
  FROM bids GROUP BY window
) AS GlobalMax
ON PerAuction.window = GlobalMax.window AND PerAuction.mx = GlobalMax.mx;
