CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE busiest (
  busiest_driver_events BIGINT,
  drivers BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO busiest
SELECT max(n) AS busiest_driver_events, count(*) AS drivers FROM (
  SELECT driver_id, count(*) AS n, tumble(interval '10 seconds') AS window
  FROM cars
  GROUP BY driver_id, window
) t
GROUP BY t.window;
