-- Updating aggregate over a DEBEZIUM source: upstream u/d envelopes
-- retract into the group accumulators (reference debezium_agg.sql;
-- count(distinct) is narrowed to count(*)+sum, see planner DISTINCT gap).
CREATE TABLE debezium_source (
  id INT PRIMARY KEY,
  customer_name TEXT,
  product_name TEXT,
  quantity INTEGER,
  price FLOAT,
  status TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/aggregate_updates.json',
  format = 'debezium_json',
  type = 'source'
);

CREATE TABLE output (
  p TEXT,
  c BIGINT,
  q BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);

INSERT INTO output
SELECT concat('p_', product_name) AS p, count(*) AS c,
       CAST(sum(quantity + 5) + 10 AS BIGINT) AS q
FROM debezium_source
GROUP BY concat('p_', product_name);
