-- Updating aggregate over a DEBEZIUM source: upstream u/d envelopes
-- retract into the group accumulators, including COUNT(DISTINCT) via
-- per-value multiplicity maps (full reference debezium_agg.sql shape —
-- the reference itself rejects updating right sides but supports this).
CREATE TABLE debezium_source (
  id INT PRIMARY KEY,
  customer_name TEXT,
  product_name TEXT,
  quantity INTEGER,
  price FLOAT,
  status TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/aggregate_updates.json',
  format = 'debezium_json',
  type = 'source'
);

CREATE TABLE output (
  p TEXT,
  c BIGINT,
  d BIGINT,
  q BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);

INSERT INTO output
SELECT concat('p_', product_name) AS p, count(*) AS c,
       count(DISTINCT customer_name) AS d,
       CAST(sum(quantity + 5) + 10 AS BIGINT) AS q
FROM debezium_source
GROUP BY concat('p_', product_name);
