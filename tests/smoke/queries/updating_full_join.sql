-- FULL OUTER JOIN with an updating right side. The reference rejects this
-- ("can't handle non-inner joins without windows", updating_full_join.sql
-- --fail marker); symmetric retractions make it work here. No WHERE on the
-- left column: null-padded rows from both sides must survive to the sink.
CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE output (
  left_counter BIGINT,
  counter_mod_2 BIGINT,
  right_count BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO output
SELECT i.counter AS left_counter, sub.counter_mod_2, sub.right_count
FROM (SELECT counter, timestamp FROM impulse WHERE counter < 5) i
FULL JOIN (
  SELECT CAST(counter % 2 AS BIGINT) AS counter_mod_2,
         count(*) AS right_count
  FROM impulse WHERE counter < 3 GROUP BY counter % 2
) sub
ON i.counter = sub.right_count;
