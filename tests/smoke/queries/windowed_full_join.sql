CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE join_output (
  driver_id BIGINT,
  other_driver BIGINT,
  pickups BIGINT,
  dropoffs BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO join_output
SELECT p.driver_id, d.driver_id AS other_driver, p.pickups, d.dropoffs
FROM (
  SELECT tumble(interval '20 seconds') AS window, driver_id, count(*) AS pickups
  FROM cars WHERE event_type = 'pickup' AND driver_id % 2 = 0
  GROUP BY window, driver_id
) p
FULL OUTER JOIN (
  SELECT tumble(interval '20 seconds') AS window, driver_id, count(*) AS dropoffs
  FROM cars WHERE event_type = 'dropoff' AND driver_id % 3 = 0
  GROUP BY window, driver_id
) d
ON p.driver_id = d.driver_id AND p.window = d.window;
