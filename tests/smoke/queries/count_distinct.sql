-- COUNT(DISTINCT x) in a tumbling window via the collect machinery
-- (reference datafusion count distinct; debezium_agg uses the same shape).
CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE distinct_output (
  start TIMESTAMP,
  et TEXT,
  drivers BIGINT,
  events BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO distinct_output
SELECT x.w.start, x.et, x.drivers, x.events FROM (
  SELECT tumble(interval '20 seconds') AS w, event_type AS et,
         count(DISTINCT driver_id) AS drivers, count(*) AS events
  FROM cars
  GROUP BY w, et
) x;
