"""Node daemon (controller/node.py) + NodeScheduler: register/heartbeat
through the REST API, worker placement on a live node, full job lifecycle
with checkpoints across the node's HTTP hop.
Reference: crates/arroyo-node/src/lib.rs:47, schedulers/mod.rs:316."""

import json
import os
import time

import pytest


def test_node_register_and_pipeline_lifecycle(tmp_path, _storage):
    from arroyo_tpu import config as cfg
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.node import NodeServer, _get
    from arroyo_tpu.controller.scheduler import NodeScheduler

    os.environ["ARROYO_TPU__CHECKPOINT__STORAGE_URL"] = cfg.config().get(
        "checkpoint.storage-url")
    # throttle the source (worker subprocess reads the env var) and
    # checkpoint fast so an epoch completes across the node HTTP hop
    # before the job drains
    os.environ["ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS"] = "15000"
    cfg.update({"checkpoint.interval-ms": 200})
    inp = tmp_path / "in.json"
    with open(inp, "w") as f:
        for i in range(200):
            f.write(json.dumps({"x": i, "timestamp": i * 1000}) + "\n")
    out_path = tmp_path / "out.json"
    sql = f"""
CREATE TABLE src (timestamp TIMESTAMP, x BIGINT)
WITH (connector = 'single_file', path = '{inp}', format = 'json', type = 'source', event_time_field = 'timestamp');
CREATE TABLE snk (x BIGINT, d BIGINT)
WITH (connector = 'single_file', path = '{out_path}', format = 'json', type = 'sink');
INSERT INTO snk SELECT x, x * 2 AS d FROM src;
"""
    db = Database()
    api = ApiServer(db).start()
    ctl = ControllerServer(db, NodeScheduler(db)).start()
    node = None
    try:
        node = NodeServer(f"http://127.0.0.1:{api.port}", slots=4).start()
        # registration is visible over REST
        nodes = _get(f"http://127.0.0.1:{api.port}/api/v1/nodes")["nodes"]
        assert [n["id"] for n in nodes] == [node.node_id]
        assert nodes[0]["slots"] == 4

        pid = db.create_pipeline("nodepipe", sql, 1)
        jid = db.create_job(pid)
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        rows = [json.loads(l) for l in open(out_path)]
        assert len(rows) == 200
        assert all(r["d"] == r["x"] * 2 for r in rows)
        # at least one checkpoint completed across the node HTTP hop
        assert any(c["state"] == "complete" for c in db.list_checkpoints(jid))
    finally:
        os.environ.pop("ARROYO_TPU__CHECKPOINT__STORAGE_URL", None)
        os.environ.pop("ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS", None)
        ctl.stop()
        if node is not None:
            node.stop()
        api.stop()


def test_node_scheduler_requires_live_node(_storage):
    """Placement without capacity must NOT block the (single-threaded)
    supervision loop: start_worker returns a lazy handle that keeps
    retrying from poll_events and reports failure at its deadline."""
    from arroyo_tpu.controller import Database
    from arroyo_tpu.controller.scheduler import LazyNodeWorkerHandle, NodeScheduler

    db = Database()
    t0 = time.monotonic()
    h = NodeScheduler(db).start_worker("SELECT 1", "j", 1, None,
                                       placement_timeout_s=0.3)
    assert time.monotonic() - t0 < 1.0  # never busy-waits in start_worker
    assert isinstance(h, LazyNodeWorkerHandle)
    assert h.alive()
    assert h.poll_events() == []  # still inside the placement window
    time.sleep(0.35)
    evs = h.poll_events()
    assert any(e["event"] == "failed" and "no live node" in e["error"] for e in evs)
    assert not h.alive()

    # stale heartbeat filtered out
    db.register_node("n1", "http://127.0.0.1:1", 4)
    with db._lock:
        db._conn.execute("UPDATE nodes SET last_heartbeat=?", (time.time() - 3600,))
        db._conn.commit()
    h2 = NodeScheduler(db).start_worker("SELECT 1", "j", 1, None,
                                        placement_timeout_s=0.2)
    assert isinstance(h2, LazyNodeWorkerHandle)
    time.sleep(0.25)
    evs = h2.poll_events()
    assert any(e["event"] == "failed" and "no live node" in e["error"] for e in evs)


def test_node_slot_reservation_released_on_spawn_failure(_storage):
    """A failed worker spawn must release its under-lock reservation, and
    concurrent reservations (value None) must count toward admission
    without raising (ADVICE r4 medium, controller/node.py)."""
    import urllib.error
    import urllib.request

    from arroyo_tpu.controller.node import NodeServer

    node = NodeServer.__new__(NodeServer)  # no registration round-trip
    node.slots = 1
    node._workers = {}
    import threading

    node._lock = threading.Lock()

    class H:
        code = None
        payload = None

        def _body(self):
            return {"job_id": "j"}  # missing "sql" -> KeyError in spawn

        def _json(self, code, payload):
            self.code, self.payload = code, payload

    # in-flight reservation from another request: must count as used,
    # not raise AttributeError on .alive()
    node._workers["pending"] = None
    h = H()
    node._start_worker(h)
    assert h.code == 409  # full: the reservation holds the only slot
    node._workers.clear()

    # spawn failure (bad body) must not leak the reservation
    with pytest.raises(KeyError):
        node._start_worker(H())
    assert node._workers == {}

    # stop() with an in-flight reservation must not raise
    node._workers["pending"] = None
    node._stop = threading.Event()

    class _Httpd:
        def shutdown(self):
            pass

    node.httpd = _Httpd()
    node.stop()
    assert node._workers == {}
