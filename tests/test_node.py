"""Node daemon (controller/node.py) + NodeScheduler: register/heartbeat
through the REST API, worker placement on a live node, full job lifecycle
with checkpoints across the node's HTTP hop.
Reference: crates/arroyo-node/src/lib.rs:47, schedulers/mod.rs:316."""

import json
import os
import time

import pytest


def test_node_register_and_pipeline_lifecycle(tmp_path, _storage):
    from arroyo_tpu import config as cfg
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.node import NodeServer, _get
    from arroyo_tpu.controller.scheduler import NodeScheduler

    os.environ["ARROYO_TPU__CHECKPOINT__STORAGE_URL"] = cfg.config().get(
        "checkpoint.storage-url")
    # throttle the source (worker subprocess reads the env var) and
    # checkpoint fast so an epoch completes across the node HTTP hop
    # before the job drains
    os.environ["ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS"] = "15000"
    cfg.update({"checkpoint.interval-ms": 200})
    inp = tmp_path / "in.json"
    with open(inp, "w") as f:
        for i in range(200):
            f.write(json.dumps({"x": i, "timestamp": i * 1000}) + "\n")
    out_path = tmp_path / "out.json"
    sql = f"""
CREATE TABLE src (timestamp TIMESTAMP, x BIGINT)
WITH (connector = 'single_file', path = '{inp}', format = 'json', type = 'source', event_time_field = 'timestamp');
CREATE TABLE snk (x BIGINT, d BIGINT)
WITH (connector = 'single_file', path = '{out_path}', format = 'json', type = 'sink');
INSERT INTO snk SELECT x, x * 2 AS d FROM src;
"""
    db = Database()
    api = ApiServer(db).start()
    ctl = ControllerServer(db, NodeScheduler(db)).start()
    node = None
    try:
        node = NodeServer(f"http://127.0.0.1:{api.port}", slots=4).start()
        # registration is visible over REST
        nodes = _get(f"http://127.0.0.1:{api.port}/api/v1/nodes")["nodes"]
        assert [n["id"] for n in nodes] == [node.node_id]
        assert nodes[0]["slots"] == 4

        pid = db.create_pipeline("nodepipe", sql, 1)
        jid = db.create_job(pid)
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        rows = [json.loads(l) for l in open(out_path)]
        assert len(rows) == 200
        assert all(r["d"] == r["x"] * 2 for r in rows)
        # at least one checkpoint completed across the node HTTP hop
        assert any(c["state"] == "complete" for c in db.list_checkpoints(jid))
    finally:
        os.environ.pop("ARROYO_TPU__CHECKPOINT__STORAGE_URL", None)
        os.environ.pop("ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS", None)
        ctl.stop()
        if node is not None:
            node.stop()
        api.stop()


def test_node_scheduler_requires_live_node(_storage):
    from arroyo_tpu.controller import Database
    from arroyo_tpu.controller.scheduler import NodeScheduler

    db = Database()
    with pytest.raises(RuntimeError, match="no live node"):
        NodeScheduler(db).start_worker("SELECT 1", "j", 1, None)
    # stale heartbeat filtered out
    db.register_node("n1", "http://127.0.0.1:1", 4)
    import arroyo_tpu.controller.db as dbm

    with db._lock:
        db._conn.execute("UPDATE nodes SET last_heartbeat=?", (time.time() - 3600,))
        db._conn.commit()
    with pytest.raises(RuntimeError, match="no live node"):
        NodeScheduler(db).start_worker("SELECT 1", "j", 1, None)
