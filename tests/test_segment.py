"""Whole-segment XLA compilation (engine/segment.py).

Covers the compile cache (hit on same schema, recompile on schema or
parallelism change), byte-exact equivalence of the compiled and interpreted
paths across the value/key/watermark/window-insert stage kinds, graceful
fallback (plan-time refusal for UDFs, runtime dtype gate, forced trace
failure — never a job failure), the SEGMENT_COMPILED/SEGMENT_FALLBACK
events, the compile metrics, the [compiled] markers in explain/top, and the
chaos axis: a worker crash mid-checkpoint under compiled segments must
restore to byte-exact goldens (carried state round-trips through the
TableManager checkpoint path because the compiled path mutates state
through the members' own methods).
"""

from __future__ import annotations

import numpy as np
import pytest

from tests.test_smoke import QUERIES, assert_outputs, build, load_sql

WIDTH = 10_000_000
SLIDE = 2_000_000


@pytest.fixture(autouse=True)
def _chained(_storage):
    from arroyo_tpu import config as cfg

    # max-delay-ms effectively off: the time-based coalescing flush makes
    # batch BOUNDARIES wall-clock-dependent (a slow first batch — e.g. the
    # XLA compile — shifts them), which reorders rows WITHIN emitted
    # window-close batches run to run on either path. Thresholds-only
    # coalescing is deterministic, so compiled vs interpreted comparisons
    # here can demand bit-identical batches, not just equal multisets.
    # min-rows 0: these tests drive small hand-built batches straight into
    # the compiled path; the production floor routes them interpreted
    cfg.update({"pipeline.chaining.enabled": True,
                "segment.compile.enabled": True,
                "segment.compile.min-rows": 0,
                "engine.coalesce.max-delay-ms": 60_000})
    yield
    cfg.update({"pipeline.chaining.enabled": False,
                "segment.compile.min-rows": 8192,
                "engine.coalesce.max-delay-ms": 5})


def _mini_graph(rows, agg: str, event_count: int = 30_000,
                price_expr=None, filter_expr=None):
    """bench-q7-shaped pipeline: nexmark source -> value(project+filter) ->
    watermark -> key -> tumbling/sliding aggregate -> vec sink. At p=1 the
    whole run fuses into one chain whose traced prefix ends at the window
    insert."""
    from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
    from arroyo_tpu.expr import Col
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "nexmark", "event_count": event_count,
        "inter_event_micros": 1000, "first_event_micros": 0,
        "include_strings": False, "columns": ["bid.auction", "bid.price"]}, 1))
    g.add_node(Node("bids", OpName.VALUE, {
        "projections": [("auction", Col("bid.auction")),
                        ("price", price_expr or Col("bid.price"))],
        "filter": filter_expr if filter_expr is not None else Col("bid")}, 1))
    g.add_node(Node("wm", OpName.WATERMARK, {
        "expr": Col(TIMESTAMP_FIELD), "interval_micros": 1_000_000}, 1))
    g.add_node(Node("key", OpName.KEY, {"keys": [("auction", Col("auction"))]}, 1))
    agg_cfg = {
        "key_fields": ["auction"],
        "aggregates": [("max_price", "max", Col("price")),
                       ("bids", "count", None)],
        "input_dtype_of": lambda e: np.dtype(np.int64),
        "backend": "numpy",
    }
    if agg == "tumbling":
        agg_cfg["width_micros"] = WIDTH
        op = OpName.TUMBLING_AGGREGATE
    else:
        agg_cfg["width_micros"] = WIDTH
        agg_cfg["slide_micros"] = SLIDE
        op = OpName.SLIDING_AGGREGATE
    g.add_node(Node("agg", op, agg_cfg, 1))
    g.add_node(Node("sink", OpName.SINK, {
        "connector": "vec", "rows": rows, "columnar": True}, 1))
    g.add_edge("src", "bids", EdgeType.FORWARD, S)
    g.add_edge("bids", "wm", EdgeType.FORWARD, S)
    g.add_edge("wm", "key", EdgeType.FORWARD, S)
    g.add_edge("key", "agg", EdgeType.SHUFFLE, S)
    g.add_edge("agg", "sink", EdgeType.FORWARD, S)
    return g


def _run(job_id: str, compile_enabled: bool, **kw) -> list:
    from arroyo_tpu import config as cfg
    from arroyo_tpu.engine import run_graph

    cfg.update({"segment.compile.enabled": compile_enabled})
    rows: list = []
    run_graph(_mini_graph(rows, kw.pop("agg", "tumbling"), **kw),
              job_id=job_id, timeout=300)
    return rows


def _canon(batches) -> list:
    """Batch list as (values, dtype) — byte-level equality surface."""
    return [{k: (np.asarray(v).tolist(), str(np.asarray(v).dtype))
             for k, v in b.columns.items()} for b in batches]


def _segment_events(job_id: str) -> list[dict]:
    from arroyo_tpu.obs.events import recorder

    return [e for e in recorder.events(job_id)
            if e["code"].startswith("SEGMENT_")]


# ------------------------------------------------------------ equivalence


def test_tumbling_compiled_byte_exact():
    interp = _run("seg-tumb-int", False)
    comp = _run("seg-tumb-cmp", True)
    assert _canon(interp) == _canon(comp)
    evs = _segment_events("seg-tumb-cmp")
    assert [e["code"] for e in evs] == ["SEGMENT_COMPILED"]
    assert evs[0]["node"] is not None and evs[0]["subtask"] == 0
    # the traced prefix covers value+wm+key+insert; the sink is the tail
    assert evs[0]["data"]["members"] == 4


def test_sliding_compiled_byte_exact():
    interp = _run("seg-slide-int", False, agg="sliding")
    comp = _run("seg-slide-cmp", True, agg="sliding")
    assert _canon(interp) == _canon(comp)
    assert [e["code"] for e in _segment_events("seg-slide-cmp")] == [
        "SEGMENT_COMPILED"]


def test_compiled_with_arithmetic_and_filter():
    """Projection arithmetic + a comparison filter trace; the filter's row
    drops must match the interpreted path's compaction exactly."""
    from arroyo_tpu.expr import BinOp, Col, Lit

    price = BinOp("+", BinOp("*", Col("bid.price"), Lit(2)), Lit(1))
    filt = BinOp("and", Col("bid"),
                 BinOp(">", Col("bid.price"), Lit(300)))
    interp = _run("seg-expr-int", False, price_expr=price, filter_expr=filt)
    comp = _run("seg-expr-cmp", True, price_expr=price, filter_expr=filt)
    assert _canon(interp) == _canon(comp)
    assert [e["code"] for e in _segment_events("seg-expr-cmp")] == [
        "SEGMENT_COMPILED"]


# ------------------------------------------------------------------ cache


def test_cache_hit_same_schema_and_metrics():
    from arroyo_tpu.engine.segment import segment_cache
    from arroyo_tpu.metrics import registry

    segment_cache.clear()  # earlier tests may have compiled this segment
    _run("seg-cache-a", True)
    compiles_a, hits_a = registry.segment_compile_stats("seg-cache-a")
    assert compiles_a >= 1 and hits_a == 0
    # same segment configs + same schema in a fresh job: the process-wide
    # cache serves the compiled entry — zero new compiles, one hit — and
    # the hit run commits into ITS OWN operator incarnation (a cached plan
    # once drove the dead first-run members: fresh watermark state saw no
    # advance and every window close vanished)
    rows_b = _run("seg-cache-b", True)
    compiles_b, hits_b = registry.segment_compile_stats("seg-cache-b")
    assert compiles_b == 0 and hits_b == 1
    assert _canon(rows_b) == _canon(_run("seg-cache-int", False))
    text = registry.prometheus_text()
    assert 'arroyo_segment_compile_seconds_count{job="seg-cache-a"}' in text
    assert 'arroyo_segment_cache_hits_total{job="seg-cache-b"} 1' in text


def test_recompile_on_schema_change():
    """A dtype change in a traced input column keys a NEW cache entry (a
    stale trace would astype-coerce instead of mis-executing, but the
    contract is recompile-per-schema)."""
    from arroyo_tpu.metrics import registry

    _run("seg-schema-a", True)
    # float prices change the traced input schema of the same segment...
    from arroyo_tpu.expr import Cast, Col

    _run("seg-schema-b", True,
         price_expr=Cast(Col("bid.price"), "float64"))
    # ...which is a different segment config here, so prove the finer
    # point at the runner level: same configs, different batch dtypes
    from arroyo_tpu.engine.segment import _schema_sig

    a = _schema_sig(_batch(auction=np.int64, n=8))
    b = _schema_sig(_batch(auction=np.float64, n=8))
    assert a != b
    assert registry.segment_compile_stats("seg-schema-b")[0] >= 1


def _batch(auction=np.int64, n: int = 8):
    from arroyo_tpu.batch import TIMESTAMP_FIELD, Batch

    return Batch({
        "bid": np.ones(n, dtype=bool),
        "bid.auction": np.arange(n).astype(auction),
        "bid.price": np.arange(n, dtype=np.int64) * 7,
        TIMESTAMP_FIELD: np.arange(n, dtype=np.int64) * 1000,
    })


def _unit_runner(parallelism: int = 1, job_id: str = "seg-unit"):
    """A ChainedOperator (value+key) + SegmentRunner with no engine: the
    cache-key and fallback behaviors are unit-testable on plain batches."""
    import arroyo_tpu
    from arroyo_tpu.engine.segment import runner_for
    from arroyo_tpu.expr import Col
    from arroyo_tpu.graph import OpName
    from arroyo_tpu.metrics import registry
    from arroyo_tpu.operators.base import OperatorContext
    from arroyo_tpu.operators.chained import ChainedOperator
    from arroyo_tpu.types import TaskInfo

    arroyo_tpu._load_operators()
    from arroyo_tpu.engine.segment import segment_marking

    members = [
        (OpName.VALUE.value, {
            "projections": [("auction", Col("bid.auction")),
                            ("price", Col("bid.price"))],
            "filter": Col("bid")}),
        (OpName.KEY.value, {"keys": [("auction", Col("auction"))]}),
    ]
    cfg = {"members": members, "compile": segment_marking(members)}
    assert cfg["compile"] is not None
    chain = ChainedOperator(cfg)
    ti = TaskInfo(job_id, "n1", chain.name(), 0, parallelism)
    ctx = OperatorContext(ti, None, None)
    chain.on_start(ctx)
    metrics = registry.task(job_id, "n1", 0)

    class Sink:
        def __init__(self):
            self.batches: list = []
            self.signals: list = []

        def collect(self, b):
            self.batches.append(b)

        def broadcast(self, s):
            self.signals.append(s)

    sink = Sink()
    runner = runner_for(chain, ctx, metrics)
    assert runner is not None
    return runner, chain, ctx, sink


def test_parallelism_keys_cache():
    """Same member configs at different parallelism use different cache
    keys (the issue's recompile-on-parallelism-change contract)."""
    r1, *_ = _unit_runner(parallelism=1)
    r2, *_ = _unit_runner(parallelism=2)
    assert r1._seg_key != r2._seg_key


def test_unit_compile_and_schema_recompile():
    from arroyo_tpu.engine.segment import segment_cache

    segment_cache.clear()
    runner, chain, ctx, sink = _unit_runner(job_id="seg-unit-a")
    runner.process_batch(_batch(n=10), ctx, sink)
    assert runner._entry is not None and not runner._fallback
    first_entry = runner._entry
    assert len(sink.batches) == 1
    out = sink.batches[0]
    assert list(out.columns) == ["auction", "price", "_timestamp", "_key"]
    # keys match the host hashing exactly (routing determinism)
    from arroyo_tpu.hashing import hash_columns

    assert np.array_equal(out.keys,
                          hash_columns([np.asarray(out["auction"])]))
    # same schema again: entry reused, no re-prepare
    runner.process_batch(_batch(n=10), ctx, sink)
    assert runner._entry is first_entry
    # dtype change: a NEW entry is compiled for the new signature
    runner.process_batch(_batch(auction=np.float64, n=10), ctx, sink)
    assert runner._entry is not first_entry and not runner._fallback
    assert len(sink.batches) == 3


# --------------------------------------------------------------- fallback


def test_plan_marking_refuses_udf():
    """A UDF anywhere in the would-be prefix stops the marking: the chain
    runs interpreted with no compile attempt (and no WARN — plan-time
    refusal is not a runtime degradation)."""
    from arroyo_tpu.engine.segment import segment_marking
    from arroyo_tpu.expr import Col
    from arroyo_tpu.graph import OpName
    from arroyo_tpu.udf import UdfExpr

    udf = UdfExpr(udf_name="f", fn=lambda x: x, vectorized=True,
                  return_dtype="int64", args=(Col("bid.price"),))
    members = [
        (OpName.VALUE.value, {"projections": [("p", udf)], "filter": None}),
        (OpName.KEY.value, {"keys": [("p", Col("p"))]}),
    ]
    assert segment_marking(members) is None


def test_untraceable_udaf_window_stops_prefix():
    """A window whose aggregate is host-resident (count_distinct) ends the
    marked prefix before it: the value/wm/key stages still compile and the
    window runs interpreted behind them."""
    from arroyo_tpu.engine.segment import segment_marking
    from arroyo_tpu.expr import Col
    from arroyo_tpu.graph import OpName

    members = [
        (OpName.VALUE.value, {
            "projections": [("auction", Col("bid.auction"))],
            "filter": Col("bid")}),
        (OpName.WATERMARK.value, {"expr": Col("_timestamp")}),
        (OpName.KEY.value, {"keys": [("auction", Col("auction"))]}),
        (OpName.TUMBLING_AGGREGATE.value, {
            "width_micros": WIDTH, "key_fields": ["auction"],
            "aggregates": [("d", "count_distinct", Col("auction"))]}),
    ]
    marking = segment_marking(members)
    assert marking == {"prefix": 3, "insert": False, "mesh": False,
                       "stop": "window: count_distinct accumulator is "
                               "host-resident"}


def test_runtime_fallback_object_column():
    """Plan-time marking cannot see dtypes; an object column referenced by
    a traced expression falls back at runtime with a SEGMENT_FALLBACK WARN
    and a correct interpreted run — never a failure."""
    from arroyo_tpu.batch import TIMESTAMP_FIELD, Batch

    runner, chain, ctx, sink = _unit_runner(job_id="seg-objcol")
    b = Batch({
        "bid": np.ones(4, dtype=bool),
        "bid.auction": np.array(["a", "b", "a", "c"], dtype=object),
        "bid.price": np.arange(4, dtype=np.int64),
        TIMESTAMP_FIELD: np.arange(4, dtype=np.int64),
    })
    runner.process_batch(b, ctx, sink)
    assert runner._fallback
    evs = _segment_events("seg-objcol")
    assert [e["code"] for e in evs] == ["SEGMENT_FALLBACK"]
    assert evs[0]["level"] == "WARN"
    assert "dtype" in evs[0]["data"]["reason"]
    # the batch still flowed — through the interpreted members
    assert len(sink.batches) == 1
    assert list(sink.batches[0].columns) == [
        "auction", "price", "_timestamp", "_key"]


def test_trace_failure_is_fallback_not_job_failure(monkeypatch):
    """Any exception out of tracing/compilation — not just the anticipated
    gates — degrades the segment, and the job's output is byte-exact."""
    import arroyo_tpu.engine.segment as seg

    seg.segment_cache.clear()

    def boom(plan):
        raise RuntimeError("injected trace failure")

    monkeypatch.setattr(seg, "_trace_fn", boom)
    comp = _run("seg-traceboom", True)
    evs = _segment_events("seg-traceboom")
    assert [e["code"] for e in evs] == ["SEGMENT_FALLBACK"]
    assert "injected trace failure" in evs[0]["data"]["reason"]
    monkeypatch.undo()
    seg.segment_cache.clear()
    interp = _run("seg-traceboom-int", False)
    assert _canon(interp) == _canon(comp)


def test_verification_mismatch_is_fallback(monkeypatch):
    """A traced function whose outputs diverge from the interpreted
    reference must never be committed: the first-batch verification
    catches it and the segment degrades."""
    import arroyo_tpu.engine.segment as seg

    seg.segment_cache.clear()
    real = seg._reference

    def skewed(plan, batch):
        want = real(plan, batch)
        for name, arr in want["cols"].items():
            if np.asarray(arr).dtype.kind in "iu" and len(arr):
                want["cols"][name] = np.asarray(arr) + 1
                break
        return want

    monkeypatch.setattr(seg, "_reference", skewed)
    runner, chain, ctx, sink = _unit_runner(job_id="seg-verify")
    runner.process_batch(_batch(n=10), ctx, sink)
    assert runner._fallback
    evs = _segment_events("seg-verify")
    assert "verification failed" in evs[0]["data"]["reason"]
    assert len(sink.batches) == 1  # interpreted output still flowed


def test_fallback_cached_negatively():
    """The second subtask (or a restored incarnation) of an untraceable
    segment reuses the negative cache entry instead of re-probing."""
    from arroyo_tpu.batch import TIMESTAMP_FIELD, Batch
    from arroyo_tpu.engine.segment import segment_cache
    from arroyo_tpu.metrics import registry

    segment_cache.clear()
    b = Batch({
        "bid": np.ones(4, dtype=bool),
        "bid.auction": np.array(["a", "b", "a", "c"], dtype=object),
        "bid.price": np.arange(4, dtype=np.int64),
        TIMESTAMP_FIELD: np.arange(4, dtype=np.int64),
    })
    r1, c1, ctx1, s1 = _unit_runner(job_id="seg-neg-a")
    r1.process_batch(b, ctx1, s1)
    r2, c2, ctx2, s2 = _unit_runner(job_id="seg-neg-b")
    r2.process_batch(b, ctx2, s2)
    assert r2._fallback
    # negative-cache reuse is NOT a cache hit: the metric counts reuse of
    # COMPILED entries only (and nothing compiled here either)
    assert registry.segment_compile_stats("seg-neg-b") == (0, 0)
    assert [e["code"] for e in _segment_events("seg-neg-b")] == [
        "SEGMENT_FALLBACK"]


def test_vacuous_first_batch_defers_compile():
    """A first batch whose hoisted filter leaves no survivors must NOT
    adopt (or cache) an unverified trace — the traced function never ran,
    so verify-then-trust would be vacuous. The compile retries on the next
    batch with survivors and verifies for real."""
    from arroyo_tpu.batch import TIMESTAMP_FIELD, Batch
    from arroyo_tpu.engine.segment import segment_cache
    from arroyo_tpu.expr import BinOp, Col, Lit
    from arroyo_tpu.graph import OpName
    from arroyo_tpu.metrics import registry
    from arroyo_tpu.operators.base import OperatorContext
    from arroyo_tpu.operators.chained import ChainedOperator
    from arroyo_tpu.types import TaskInfo

    segment_cache.clear()
    from arroyo_tpu.engine.segment import runner_for, segment_marking

    members = [
        (OpName.VALUE.value, {
            "projections": [("p", Col("bid.price"))],
            # selective: only prices > threshold survive
            "filter": BinOp(">", Col("bid.price"), Lit(100))}),
        (OpName.KEY.value, {"keys": [("p", Col("p"))]}),
    ]
    cfg = {"members": members, "compile": segment_marking(members)}
    chain = ChainedOperator(cfg)
    ctx = OperatorContext(TaskInfo("seg-vac", "n1", chain.name(), 0, 1),
                          None, None)
    chain.on_start(ctx)
    runner = runner_for(chain, ctx, registry.task("seg-vac", "n1", 0))

    class Sink:
        batches: list = []

        def collect(self, b):
            Sink.batches.append(b)

        def broadcast(self, s):
            pass

    Sink.batches = []

    def mk(prices):
        n = len(prices)
        return Batch({"bid.price": np.asarray(prices, dtype=np.int64),
                      TIMESTAMP_FIELD: np.arange(n, dtype=np.int64)})

    # every row filtered: hoist selectivity 0 -> traced fn never runs
    runner.process_batch(mk([1, 2, 3, 4]), ctx, Sink())
    assert runner._entry is None and not runner._fallback
    assert Sink.batches == []  # nothing flows on either path
    # next batch has survivors: compile + verify for real, rows flow
    runner.process_batch(mk([1, 200, 300, 2]), ctx, Sink())
    assert runner._entry is not None and not runner._fallback
    assert len(Sink.batches) == 1
    assert np.asarray(Sink.batches[0]["p"]).tolist() == [200, 300]


def test_steady_state_execute_failure_is_fallback(monkeypatch):
    """An execution failure AFTER the verified first batch (e.g. a new
    padded shape failing to XLA-compile) degrades the segment — execute is
    pure, so the batch replays interpreted and the job never fails."""
    import arroyo_tpu.engine.segment as seg

    seg.segment_cache.clear()
    runner, chain, ctx, sink = _unit_runner(job_id="seg-latefail")
    runner.process_batch(_batch(n=10), ctx, sink)
    assert runner._entry is not None

    def boom(self, batch, job_id, observe=True, min_rows=0):
        raise RuntimeError("injected late XLA failure")

    monkeypatch.setattr(seg.CompiledSegment, "execute", boom)
    runner.process_batch(_batch(n=10), ctx, sink)
    assert runner._fallback
    assert len(sink.batches) == 2  # the failing batch still flowed
    evs = _segment_events("seg-latefail")
    assert evs[-1]["code"] == "SEGMENT_FALLBACK"
    assert "injected late XLA failure" in evs[-1]["data"]["reason"]


def test_min_rows_floor_runs_interpreted():
    """Batches below segment.compile.min-rows never pay the jit dispatch:
    they take the interpreted members, and the mixed stream is still
    correct (the floor only picks between verified-equal paths)."""
    from arroyo_tpu import config as cfg

    cfg.update({"segment.compile.min-rows": 64})
    try:
        runner, chain, ctx, sink = _unit_runner(job_id="seg-floor")
        runner.process_batch(_batch(n=8), ctx, sink)
        assert runner._entry is None  # small batch: no compile attempted
        runner.process_batch(_batch(n=128), ctx, sink)
        assert runner._entry is not None  # big batch compiled
        runner.process_batch(_batch(n=8), ctx, sink)  # small again: interp
        assert len(sink.batches) == 3
        from arroyo_tpu.hashing import hash_columns

        for b in sink.batches:
            assert list(b.columns) == ["auction", "price", "_timestamp",
                                       "_key"]
            assert np.array_equal(
                b.keys, hash_columns([np.asarray(b["auction"])]))
    finally:
        cfg.update({"segment.compile.min-rows": 0})


def test_disabled_by_config():
    from arroyo_tpu import config as cfg
    from arroyo_tpu.engine.segment import runner_for

    runner, chain, ctx, sink = _unit_runner(job_id="seg-off")
    cfg.update({"segment.compile.enabled": False})
    assert runner_for(chain, ctx, None) is None


# ----------------------------------------------------------- observability


def test_explain_top_compiled_marker():
    from arroyo_tpu.metrics import merge_job_metrics, registry
    from arroyo_tpu.obs.profile import job_profile, render_explain
    from arroyo_tpu.obs.topview import render

    _run("seg-marker", True)
    metrics = merge_job_metrics([registry.job_metrics("seg-marker")])
    chained_ops = [op for op, m in metrics.items()
                   if m.get("segment_compiled")]
    assert chained_ops, "no operator carries the compiled flag"
    frame = render({"id": "seg-marker", "state": "Finished"}, metrics)
    assert "[compiled]" in frame
    profile = job_profile(metrics)
    text = render_explain(
        [{"id": op, "op": "chained", "parallelism": 1} for op in metrics],
        [], profile, {"id": "seg-marker", "state": "Finished"})
    assert "[compiled]" in text


def test_executed_graph_view_marks_compilable():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "smoke"))
    try:
        import udfs  # noqa: F401
    finally:
        sys.path.pop(0)
    from arroyo_tpu.sql.planner import executed_graph_view

    sql = load_sql("tumbling_aggregates", "/tmp/seg_view_out.json")
    nodes, _edges = executed_graph_view(sql)
    chained = [n for n in nodes if n["op"] == "chained"]
    assert chained and any(n.get("compilable") for n in chained)


# ----------------------------------------------------- smoke-family sweep


@pytest.mark.parametrize("name", QUERIES)
def test_smoke_families_compiled(name, tmp_path, _storage):
    """Every smoke family runs to byte-exact goldens with compilation ON
    and actively engaged (min-rows floored to 0 by the fixture, so the
    512-row source batches hit the compiled path, not the cost floor).
    Families whose segments cannot trace — string keys, UDFs, sessions —
    exercise the marking/fallback gates and MUST still match goldens."""
    out = str(tmp_path / "out.json")
    eng = build(load_sql(name, out), 1, f"{name}-segcomp")
    eng.run_to_completion(timeout=180)
    assert_outputs(name, out)


# ------------------------------------------------------------- chaos axis


@pytest.mark.chaos
@pytest.mark.parametrize("name", ["tumbling_aggregates", "sliding_window"])
def test_chaos_crash_restore_compiled(name, tmp_path, _storage):
    """Worker crash mid-epoch-2-checkpoint with compiled segments: the
    carried operator state (window partials, late boundaries, watermark
    marks) must round-trip the TableManager checkpoint path and restore to
    byte-exact goldens — the compiled path mutates state only through the
    members' own methods, so this axis proves that claim end to end."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.state.tables import latest_complete_checkpoint

    out = str(tmp_path / "out.json")
    sql = load_sql(name, out)
    job_id = f"{name}-seg-chaos"
    cfg.update({"testing.source-gate-epochs": 2})
    inj = faults.install("worker:crash@barrier=2&step=1", seed=1337)
    try:
        eng = build(sql, 2, job_id)
        eng.start()
        assert eng.checkpoint_and_wait(1, timeout=60), "epoch 1 incomplete"
        with pytest.raises(RuntimeError, match="injected"):
            if eng.checkpoint_and_wait(2, timeout=60):
                raise AssertionError("epoch 2 completed despite crash")
            eng.join(timeout=60)
    finally:
        faults.clear()
        cfg.update({"testing.source-gate-epochs": 0})
    assert inj.fired_log, "crash fault never fired"
    storage_url = cfg.config().get("checkpoint.storage-url")
    assert latest_complete_checkpoint(storage_url, job_id) == 1

    eng2 = build(sql, 2, job_id, restore_epoch=1)
    eng2.run_to_completion(timeout=180)
    # compiled segments genuinely ran across the crash/restore boundary
    # (the pre-agg chain during phase 1, the post-agg chain once windows
    # close after the restore) and never fell back
    evs = _segment_events(job_id)
    assert any(e["code"] == "SEGMENT_COMPILED" for e in evs), \
        "chaos axis ran without a compiled segment"
    assert_outputs(name, out)
