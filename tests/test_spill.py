"""Tiered state backend tests (state/spill.py, ISSUE 14).

Three layers:

- annex units: spill/probe/tombstone ownership, bloom + zone-map pruning
  (including the bloom false-positive path), newest-run-wins, TTL scans,
  generation compaction, deterministic clock-LRU eviction, and manifest
  checkpoint/restore in replay-equivalence normal form;
- fault sites: ``spill_write``/``spill_probe``/``spill_compact`` injected
  failures degrade (re-pin hot + SPILL_FALLBACK + backoff / in-place
  retry / keep old generations) — never corrupt;
- the smoke family: ``spill_keyspace`` (keyspace ~10x a tiny budget) runs
  to byte-exact goldens WITH spill actively engaged (metrics nonzero),
  through checkpoint/stop/restore-at-new-parallelism, worker crash
  mid-checkpoint with spilled state present, and ``spill_write:fail``
  mid-stream; the updating-join families prove the side-store tier.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from arroyo_tpu import config as cfg
from arroyo_tpu import faults
from arroyo_tpu.obs.events import recorder
from arroyo_tpu.state.spill import (
    BloomFilter,
    KeyedSpillAnnex,
    RowSpillAnnex,
    cleanup_spill_runs,
    merge_spill_stats,
)
from arroyo_tpu.types import TaskInfo

SMOKE = os.path.join(os.path.dirname(__file__), "smoke")


def ti(subtask=0, parallelism=1, job="spill-job", node="op_1"):
    return TaskInfo(job, node, "op", subtask, parallelism)


def keyed_annex(tmp_path, subtask=0, parallelism=1, job="spill-job",
                **over) -> KeyedSpillAnnex:
    cfg.update({"state.spill.partition-count": 8,
                "state.spill.max-runs": 4, **over})
    return KeyedSpillAnnex(ti(subtask, parallelism, job),
                           str(tmp_path / "st"), "s")


def packed(v, ts=100):
    """Annex pack contract: event time rides at index -1."""
    return ("payload", v, ts)


def spill_all(annex: KeyedSpillAnnex, items: dict[int, tuple]) -> None:
    by_p: dict[int, list] = {}
    for h, v in items.items():
        by_p.setdefault(annex.partition_of(h), []).append((h, v))
    for p in sorted(by_p):
        assert annex.spill(p, by_p[p])


# ------------------------------------------------------------------ units


def test_bloom_no_false_negatives_and_serialization():
    keys = np.arange(1, 5000, 7, dtype=np.uint64) * np.uint64(2654435761)
    b = BloomFilter.build(keys)
    assert b.contains(keys).all()
    b2 = BloomFilter.from_state(b.state())
    assert b2.contains(keys).all()
    # false-positive rate on disjoint keys stays in the expected band
    others = (np.arange(1, 5000, 7, dtype=np.uint64) + np.uint64(3)) * \
        np.uint64(2654435761)
    fp = b.contains(others).mean()
    assert fp < 0.05, fp


def test_spill_lookup_promote_tombstone(tmp_path, _storage):
    annex = keyed_annex(tmp_path)
    items = {h: packed(h, ts=100 + h) for h in range(1, 40)}
    spill_all(annex, items)
    assert annex.has_runs()
    got = annex.lookup_many([3, 7, 12345])
    assert got == {3: packed(3, 103), 7: packed(7, 107)}
    # promote disowns: the hot tier is the single owner now — a second
    # probe must NOT resurrect the stale spilled copy
    assert annex.lookup_many([3, 7]) == {}
    # un-promoted keys still resolve
    assert annex.lookup_many([5]) == {5: packed(5, 105)}


def test_newest_run_wins_and_dead_rows_shadow(tmp_path, _storage):
    annex = keyed_annex(tmp_path)
    h = 11
    p = annex.partition_of(h)
    assert annex.spill(p, [(h, packed("old", 50))])
    # promote (tombstones) then respill a fresh copy: the tombstone folds
    # into the new run and the fresh row supersedes it
    assert annex.lookup_many([h]) == {h: packed("old", 50)}
    assert annex.spill(p, [(h, packed("new", 60))])
    assert annex.lookup_many([h]) == {h: packed("new", 60)}
    # a key that DIED while tombstoned: respill with no fresh copy writes
    # a dead row that shadows every older copy
    assert annex.lookup_many([h]) == {}  # promoted again above
    assert annex.spill(p, [])
    assert annex.lookup_many([h]) == {}


def test_zone_map_and_bloom_prune_probe_files(tmp_path, _storage):
    annex = keyed_annex(tmp_path)
    # two runs in two distinct partitions; a probe for a key of partition A
    # must touch only partition A's file
    pc = annex.pc
    width = 2 ** 64 // pc
    h_a = 5                       # partition 0
    h_b = width * (pc // 4) + 5   # a higher partition, still signed-positive
    pa, pb = annex.partition_of(h_a), annex.partition_of(h_b)
    assert pa != pb
    assert annex.spill(pa, [(h_a, packed("a"))])
    assert annex.spill(pb, [(h_b, packed("b"))])
    before = annex.stats.probe_files.sum
    assert annex.lookup_many([h_a]) == {h_a: packed("a")}
    assert annex.stats.probe_files.sum - before == 1  # one file touched
    # bloom false-positive path: a key inside the zone hull but absent
    # resolves to nothing (at worst it costs a read, never a wrong value)
    assert annex.lookup_many([h_a + 1]) == {}


def test_scan_expired_newest_version_semantics(tmp_path, _storage):
    annex = keyed_annex(tmp_path)
    h_old, h_fresh = 21, 22
    p = annex.partition_of(h_old)
    assert p == annex.partition_of(h_fresh)
    assert annex.spill(p, [(h_old, packed("o", 100)), (h_fresh, packed("f", 100))])
    # h_fresh gets a NEWER copy in a later run: its newest ts is beyond the
    # cutoff, so only h_old expires
    assert annex.lookup_many([h_fresh]) == {h_fresh: packed("f", 100)}
    assert annex.spill(p, [(h_fresh, packed("f2", 500))])
    out = annex.scan_expired(200, exclude=set())
    assert out == [(h_old, packed("o", 100))]
    # scan promotes: the expired key is now disowned
    assert annex.lookup_many([h_old]) == {}
    # nothing re-expires, and the zone gate keeps later scans free
    assert annex.scan_expired(200, exclude=set()) == []


def test_scan_expired_reads_dead_marker_only_runs(tmp_path, _storage):
    """A tombstone-only run (rows==0) still shadows older alive copies on
    the expiry scan — skipping it would resurrect and double-retract a
    dead key (the lookup path and the scan path must agree on liveness)."""
    annex = keyed_annex(tmp_path)
    h = 17
    p = annex.partition_of(h)
    assert annex.spill(p, [(h, packed("v", 10))])
    assert annex.lookup_many([h]) == {h: packed("v", 10)}  # promote+tombstone
    assert annex.spill(p, [])  # the key died hot: dead-marker-only run
    assert annex.scan_expired(100, exclude=set()) == []


def test_row_adopt_shared_run_floor_is_conservative(tmp_path, _storage):
    """A rescale-shared run's alive floor resets to the run's global
    min_ts: the old owner's floor was computed under ITS key range and can
    sit above (or read None against) rows alive in the new owner's slice —
    an optimistic floor would let the watermark pass un-emitted rows."""
    owner = RowSpillAnnex(ti(0, 1, job="floor"), str(tmp_path / "st"),
                          "left", n_vals=1)
    keys = np.array([5, -5], dtype=np.int64)  # one per future half-range
    ts = np.array([10, 50], dtype=np.int64)
    assert owner.spill_rows(keys, ts, np.zeros(2, np.int64),
                            np.zeros(2, bool),
                            [np.array(["a", "b"], dtype=object)])
    # promote the ts=10 row: the owner's floor advances to 50
    owner.probe(np.array([5], dtype=np.int64))
    assert owner.runs[0]["alive_min_ts"] == 50
    m = owner.manifest()
    half = RowSpillAnnex(ti(1, 2, job="floor"), str(tmp_path / "st"),
                         "left", n_vals=1)
    half.adopt([m, {"kind": "rows", "writer": 0, "runs": []}])
    # conservative reset: global min_ts, not the old owner's range floor
    assert half.runs[0]["alive_min_ts"] == 10


def test_restore_with_spill_disabled_fails_loudly(tmp_path, _storage):
    """A checkpoint whose manifest references spilled runs cannot restore
    with state.spill.enabled=false: the cold keyspace lives only in run
    files, and silently re-aggregating those keys from identity is the
    corruption this guard exists to prevent."""
    from arroyo_tpu.operators.base import OperatorContext
    from arroyo_tpu.operators.updating_aggregate import UpdatingAggregate
    from arroyo_tpu.state.tables import TableManager

    cfg.update({"state.spill.enabled": False})
    tm = TableManager(ti(job="noSpill"), str(tmp_path / "st"))
    tm.global_keyed("s__spill").insert(0, {
        "kind": "keyed", "runs": [{"file": "run-s-s000-e0000001-000001.parquet"}]})
    op = UpdatingAggregate({"key_fields": [], "aggregates": [("c", "count", None)],
                            "input_dtype_of": lambda e: np.dtype(np.int64)})
    ctx = OperatorContext(ti(job="noSpill"), None, tm)
    with pytest.raises(RuntimeError, match="state.spill.enabled"):
        op.on_start(ctx)
    # an empty manifest (nothing ever spilled) restores fine
    tm.global_keyed("s__spill").insert(0, {"kind": "keyed", "runs": []})
    op2 = UpdatingAggregate({"key_fields": [], "aggregates": [("c", "count", None)],
                             "input_dtype_of": lambda e: np.dtype(np.int64)})
    op2.on_start(ctx)


def test_compaction_merges_generations(tmp_path, _storage):
    annex = keyed_annex(tmp_path, **{"state.spill.max-runs": 3})
    h1, h2 = 31, 33
    p = annex.partition_of(h1)
    assert p == annex.partition_of(h2)
    # five generations of the same key (promote + respill each round)
    for i in range(5):
        if i:
            assert annex.lookup_many([h1]) == {h1: packed(i - 1, 100 + i - 1)}
        assert annex.spill(p, [(h1, packed(i, 100 + i))] +
                           ([(h2, packed("x", 99))] if i == 0 else []))
    group = [r for r in annex.runs]
    assert len(group) <= 3 + 1  # compaction bounded the generations
    assert annex.stats.compactions >= 1
    assert any(int(r.get("gen", 0)) >= 1 for r in annex.runs)
    # newest values survived the merges; h2's single old copy did too
    assert annex.lookup_many([h1, h2]) == {h1: packed(4, 104),
                                           h2: packed("x", 99)}


def test_deterministic_eviction_order_across_restore(tmp_path, _storage):
    annex = keyed_annex(tmp_path)
    # touch partitions in a fixed order; victims must come back coldest
    # first with partition id as the tie-break — and identically after a
    # manifest restore (the PR 10 dict-order bug class)
    for p in (3, 1, 5):
        annex.clock += 1
        annex.last_access[p] = annex.clock
    hot = {0: 4, 1: 4, 3: 4, 5: 4}
    v1 = annex.pick_victims(hot, excess_entries=8)
    assert v1 == [0, 3]  # untouched first, then oldest touch
    annex2 = keyed_annex(tmp_path)
    annex2.adopt([annex.manifest()])
    assert annex2.pick_victims(hot, excess_entries=8) == v1
    assert annex2.clock == annex.clock


def test_manifest_roundtrip_normal_form(tmp_path, _storage):
    annex = keyed_annex(tmp_path)
    items = {h: packed(h) for h in range(50, 90)}
    spill_all(annex, items)
    annex.lookup_many([55, 60])  # tombstones ride the manifest
    m = annex.manifest()
    fresh = keyed_annex(tmp_path)
    fresh.adopt([m])
    # replay-equivalence normal form: same run files in the same order,
    # same tombstones, same probe results for every key
    assert [r["file"] for r in fresh.runs] == [r["file"] for r in annex.runs]
    assert {p: set(s) for p, s in fresh.tombstones.items() if s} == \
        {p: set(s) for p, s in annex.tombstones.items() if s}
    want = {h: packed(h) for h in range(50, 90) if h not in (55, 60)}
    assert fresh.lookup_many(list(range(50, 90))) == want
    assert fresh.next_seq == annex.next_seq


def test_rescale_adopt_filters_by_key_range(tmp_path, _storage):
    annex = keyed_annex(tmp_path, subtask=0, parallelism=1)
    items = {h: packed(h) for h in
             [5, -5, 2 ** 62, -(2 ** 62)]}  # spread across the hash space
    spill_all(annex, items)
    m = annex.manifest()
    halves = [keyed_annex(tmp_path, subtask=s, parallelism=2)
              for s in (0, 1)]
    for a in halves:
        a.adopt([m])
    for h, v in items.items():
        owners = [a for a in halves
                  if a.key_lo <= (h & (2 ** 64 - 1)) <= a.key_hi]
        assert len(owners) == 1
        assert owners[0].lookup_many([h]) == {h: v}


# ------------------------------------------------------------ fault sites


def test_spill_write_failure_degrades_and_backs_off(tmp_path, _storage):
    annex = keyed_annex(tmp_path, job="spill-degrade")
    faults.install("spill_write:fail", seed=1)
    try:
        assert not annex.spill(0, [(1, packed("a"))])
    finally:
        faults.clear()
    assert annex.stats.failures == 1
    assert not annex.has_runs()  # nothing registered: state stays hot
    evs = recorder.events("spill-degrade")
    assert any(e["code"] == "SPILL_FALLBACK" for e in evs)
    # deterministic call-count backoff, then full recovery
    for _ in range(16):
        assert not annex.spill(0, [(1, packed("a"))])
    assert annex.spill(0, [(1, packed("a"))])
    assert annex.lookup_many([1]) == {1: packed("a")}
    recorder.clear_job("spill-degrade")


def test_spill_write_fail_at_epoch_degrades_not_corrupts(tmp_path, _storage):
    """The ``fail@epoch`` chaos shape: spill writes fail only while the
    annex is inside the targeted epoch — the partition stays hot through
    the bad epoch and spills cleanly in the next, with every value
    resolving correctly throughout."""
    annex = keyed_annex(tmp_path, job="spill-epoch")
    annex.epoch = 1
    faults.install("spill_write:fail@epoch=1", seed=1)
    try:
        assert not annex.spill(0, [(1, packed("a"))])
        assert not annex.has_runs()
        annex.epoch = 2
        annex._skip_spills = 0  # the epoch moved on; retry immediately
        assert annex.spill(0, [(1, packed("a"))])
    finally:
        faults.clear()
    assert annex.lookup_many([1]) == {1: packed("a")}
    assert "-e0000002-" in annex.runs[0]["file"]  # epoch-tagged for GC
    recorder.clear_job("spill-epoch")


def test_spill_probe_failure_retries_in_place(tmp_path, _storage):
    annex = keyed_annex(tmp_path)
    assert annex.spill(annex.partition_of(7), [(7, packed("v"))])
    faults.install("spill_probe:fail_once", seed=1)
    try:
        assert annex.lookup_many([7]) == {7: packed("v")}
    finally:
        faults.clear()


def test_spill_compact_failure_keeps_old_generations(tmp_path, _storage):
    annex = keyed_annex(tmp_path, job="spill-cfail",
                        **{"state.spill.max-runs": 2})
    h = 41
    p = annex.partition_of(h)
    faults.install("spill_compact:fail", seed=1)
    try:
        for i in range(4):
            if i:
                annex.lookup_many([h])
            assert annex.spill(p, [(h, packed(i, 100 + i))])
    finally:
        faults.clear()
    # the merge failed: generations pile up but every probe still resolves
    # the newest copy — degraded read amplification, zero corruption
    assert annex.stats.failures >= 1
    assert all(int(r.get("gen", 0)) == 0 for r in annex.runs)
    assert annex.lookup_many([h]) == {h: packed(3, 103)}
    evs = recorder.events("spill-cfail")
    assert any(e["code"] == "SPILL_FALLBACK" for e in evs)
    recorder.clear_job("spill-cfail")


# -------------------------------------------------------------- row annex


def test_row_annex_spill_probe_expire(tmp_path, _storage):
    annex = RowSpillAnnex(ti(job="spill-rows"), str(tmp_path / "st"),
                          "left", n_vals=2)
    keys = np.array([1, 1, 2, 3], dtype=np.int64)
    ts = np.array([10, 20, 30, 40], dtype=np.int64)
    mc = np.array([0, 1, 2, 0], dtype=np.int64)
    ne = np.array([True, False, False, True], dtype=bool)
    v0 = np.array(["a", "b", "c", "d"], dtype=object)
    v1 = np.array([1, 2, 3, 4], dtype=object)
    assert annex.spill_rows(keys, ts, mc, ne, [v0, v1])
    assert annex.alive_rows() == 4
    assert annex.oldest_ts() == 10
    # probe key 1: BOTH its rows promote (match counts intact) and their
    # slots die in the run
    k, t, m, n, vals = annex.probe(np.array([1], dtype=np.int64))
    assert sorted(k.tolist()) == [1, 1]
    assert sorted(t.tolist()) == [10, 20]
    assert sorted(m.tolist()) == [0, 1]
    assert annex.alive_rows() == 2
    assert annex.oldest_ts() == 30  # floor advanced past the promoted rows
    assert annex.probe(np.array([1], dtype=np.int64)) is None
    # expiry kills old alive rows in place and drops empty runs
    assert annex.expire(cutoff=35) == 1  # row with ts=30
    assert annex.alive_rows() == 1
    assert annex.oldest_ts() == 40
    # manifest roundtrip preserves dead sets
    fresh = RowSpillAnnex(ti(job="spill-rows"), str(tmp_path / "st"),
                          "left", n_vals=2)
    fresh.adopt([annex.manifest()])
    assert fresh.alive_rows() == 1
    seg = fresh.probe(np.array([3], dtype=np.int64))
    assert seg is not None and seg[0].tolist() == [3]


def test_merge_spill_stats():
    from arroyo_tpu.state.spill import SpillStats

    s1, s2 = SpillStats(), SpillStats()
    s1.bytes_total, s2.bytes_total = 100, 50
    s1.probe_files.observe(2)
    s2.probe_files.observe(5)
    merged = merge_spill_stats([
        {"bytes_total": s1.bytes_total, "hot": 3, "cold": 1,
         "probe_files": s1.probe_files},
        None,
        {"bytes_total": s2.bytes_total, "hot": 2, "cold": 2,
         "probe_files": s2.probe_files}])
    assert merged["bytes_total"] == 150
    assert merged["cold"] == 3
    assert merged["probe_files"].count == 2
    assert merge_spill_stats([None]) is None


# ---------------------------------------------------------------- spill GC


def test_cleanup_spill_runs(tmp_path, _storage):
    from arroyo_tpu.state import storage as st

    root = str(tmp_path / "gcroot")
    job = "gcjob"
    spill_dir = os.path.join(root, job, "spill", "operator-op_1")
    st.makedirs(spill_dir)
    names = {
        "referenced": "run-s-s000-e0000001-000001.parquet",
        "orphan_old": "run-s-s000-e0000001-000002.parquet",
        "fresh": "run-s-s000-e0000005-000003.parquet",
    }
    for n in names.values():
        st.write_bytes(os.path.join(spill_dir, n), b"x")
    opdir = os.path.join(root, job, "checkpoints", "checkpoint-0000005",
                         "operator-op_1")
    st.makedirs(opdir)
    import json

    st.write_text(os.path.join(opdir, "metadata-000.json"), json.dumps({
        "subtask_index": 0, "watermark_micros": None,
        "files": [{"table": "s__spill", "file": "table-s__spill-000.bin",
                   "kind": "global_keyed",
                   "spill_runs": [names["referenced"]]}],
    }))
    removed = cleanup_spill_runs(root, job, newest_complete_epoch=5)
    assert removed == 1
    left = set(st.listdir(spill_dir))
    assert names["referenced"] in left      # a live checkpoint needs it
    assert names["fresh"] in left           # epoch tag >= newest: protected
    assert names["orphan_old"] not in left  # unreferenced and old: gone


def test_manifest_runs_lifted_into_checkpoint_metadata(tmp_path, _storage):
    """TableManager.checkpoint exposes a __spill table's referenced run
    files in the metadata json (what the GC scans), and compact_operator
    preserves the union when merging manifest shards."""
    import json

    from arroyo_tpu.state import storage as st
    from arroyo_tpu.state.tables import TableManager, compact_operator, operator_dir

    root = str(tmp_path / "ck")
    metas = []
    for sub in (0, 1):
        tm = TableManager(ti(subtask=sub, parallelism=2, job="mjob"), root)
        tm.global_keyed("s__spill").insert(sub, {
            "kind": "keyed",
            "runs": [{"file": f"run-s-s{sub:03d}-e0000000-000001.parquet"}]})
        metas.append(tm.checkpoint(1, None))
    for m in metas:
        fm = next(f for f in m["files"] if f["table"] == "s__spill")
        assert fm["spill_runs"] == [
            f"run-s-s{m['subtask_index']:03d}-e0000000-000001.parquet"]
    compact_operator(root, "mjob", 1, "op_1")
    opdir = operator_dir(root, "mjob", 1, "op_1")
    merged_runs = set()
    for fn in st.listdir(opdir):
        if fn.startswith("metadata-"):
            meta = json.loads(st.read_text(os.path.join(opdir, fn)))
            for f in meta["files"]:
                merged_runs.update(f.get("spill_runs", ()))
    assert merged_runs == {"run-s-s000-e0000000-000001.parquet",
                           "run-s-s001-e0000000-000001.parquet"}


# ----------------------------------------------------------- health rule


def test_memory_pressure_health_rule(_storage):
    from arroyo_tpu.obs.health import HealthMonitor

    cfg.update({"state.spill.budget-bytes": 1000,
                "health.fire-ticks": 2, "health.clear-ticks": 2})
    transitions = []
    mon = HealthMonitor("hj", on_transition=lambda o, n, d: transitions.append(n))
    over = {"op": {"per_subtask": {"0": {"state_bytes": {"s": 950}}}}}
    under = {"op": {"per_subtask": {"0": {"state_bytes": {"s": 100}}}}}
    d = mon.evaluate(over)
    rule = next(r for r in d["rules"] if r["rule"] == "memory-pressure")
    assert rule["breaching"] and not rule["firing"]  # hysteresis arms
    d = mon.evaluate(over)
    rule = next(r for r in d["rules"] if r["rule"] == "memory-pressure")
    assert rule["firing"] and d["state"] == "degraded"
    assert transitions == ["degraded"]
    mon.evaluate(under)
    d = mon.evaluate(under)
    assert d["state"] == "ok"
    assert transitions == ["degraded", "ok"]


# ------------------------------------------------------- smoke + chaos


def _smoke():
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    try:
        import test_smoke as ts
    finally:
        sys.path.pop(0)
    return ts


def _spill_lines(job_id: str) -> dict[str, str]:
    from arroyo_tpu.metrics import registry

    return {l.split("{")[0] + ("/cold" if 'state="cold"' in l else "")
            : l for l in registry.prometheus_text().splitlines()
            if l.startswith("arroyo_spill") and f'job="{job_id}"' in l}


def assert_spill_engaged(job_id: str, require_bytes: bool = True,
                         require_probes: bool = True) -> None:
    """The acceptance gate: spill metrics NONZERO in the run — bytes were
    actually written, partitions actually went cold, probes were counted.
    ``require_bytes=False`` for a freshly-restored incarnation whose own
    counters start at zero: it proves engagement via adopted cold
    partitions and probe traffic instead."""
    from arroyo_tpu.metrics import registry

    text = registry.prometheus_text()
    mine = [l for l in text.splitlines() if f'job="{job_id}"' in l]
    cold = [l for l in mine if l.startswith("arroyo_spill_partitions")
            and 'state="cold"' in l]
    probes = [l for l in mine
              if l.startswith("arroyo_spill_probe_files_count")]
    if require_bytes:
        by = [l for l in mine if l.startswith("arroyo_spill_bytes_total")]
        assert any(int(l.rsplit(" ", 1)[1]) > 0 for l in by), by
    assert any(int(l.rsplit(" ", 1)[1]) > 0 for l in cold), cold
    if require_probes:
        assert any(float(l.rsplit(" ", 1)[1]) > 0 for l in probes), probes
    evs = recorder.events(job_id)
    assert any(e["code"] == "SPILL_STARTED" for e in evs)


SPILL_CFG = {
    "state.spill.enabled": True,
    "state.spill.budget-bytes": 32768,  # keyspace est. ~10x this
    "state.spill.target-file-bytes": 16384,
}


def test_smoke_spill_keyspace_golden_with_spill_engaged(tmp_path, _storage):
    ts = _smoke()
    cfg.update(SPILL_CFG)
    out = str(tmp_path / "out.json")
    job = "spill-smoke-p1"
    eng = ts.build(ts.load_sql("spill_keyspace", out), 1, job)
    eng.run_to_completion(timeout=180)
    ts.assert_outputs("spill_keyspace", out)
    assert_spill_engaged(job)
    recorder.clear_job(job)


def test_smoke_spill_checkpoint_restore_rescale(tmp_path, _storage):
    """The smoke harness's (b)/(c) modes under active spill: checkpoint at
    epochs 1-3 at p=2, stop, restore at p=3, run to byte-exact goldens —
    the tiered layout (runs + tombstones + clocks) rebuilds across a
    parallelism change."""
    ts = _smoke()
    cfg.update({**SPILL_CFG, "testing.source-gate-epochs": 3})
    out = str(tmp_path / "out.json")
    job = "spill-smoke-restore"
    sql = ts.load_sql("spill_keyspace", out)
    eng = ts.build(sql, 2, job)
    eng.start()
    for ep in (1, 2, 3):
        assert eng.checkpoint_and_wait(ep, timeout=60), f"epoch {ep}"
    eng.stop()
    eng.join(timeout=60)
    cfg.update({"testing.source-gate-epochs": 0})
    eng2 = ts.build(sql, 3, job, restore_epoch=3)
    eng2.run_to_completion(timeout=180)
    ts.assert_outputs("spill_keyspace", out)
    assert_spill_engaged(job)
    recorder.clear_job(job)


@pytest.mark.chaos
def test_chaos_worker_crash_mid_checkpoint_with_spilled_state(tmp_path, _storage):
    """Crash AFTER epoch-2 state files land but before the epoch completes,
    with spilled runs live: the torn epoch is ignored, epoch 1's manifest
    restores the tiered layout, and recovery is byte-exact."""
    from arroyo_tpu.state.tables import latest_complete_checkpoint

    ts = _smoke()
    cfg.update({**SPILL_CFG, "testing.source-gate-epochs": 2})
    out = str(tmp_path / "out.json")
    job = "spill-chaos-crash"
    sql = ts.load_sql("spill_keyspace", out)
    inj = faults.install("worker:crash@barrier=2&step=1", seed=1337)
    try:
        eng = ts.build(sql, 2, job)
        eng.start()
        assert eng.checkpoint_and_wait(1, timeout=60), "epoch 1"
        # barrier 2 crashes the SOURCE before the barrier ever reaches the
        # aggregate, so teardown races the aggregate still chewing the
        # pre-gate half of the input in the background. Wait for spill to
        # provably engage (SPILL_STARTED fires only after run files hit
        # disk) before arming the crash epoch — "runs on disk at crash
        # time" must be a guarantee, not a scheduling accident.
        deadline = time.monotonic() + 60
        while not any(e["code"] == "SPILL_STARTED"
                      for e in recorder.events(job)):
            assert time.monotonic() < deadline, "spill never engaged"
            time.sleep(0.05)
        with pytest.raises(RuntimeError, match="injected"):
            if eng.checkpoint_and_wait(2, timeout=60):
                raise AssertionError("epoch 2 completed despite the crash")
            eng.join(timeout=60)
    finally:
        faults.clear()
        cfg.update({"testing.source-gate-epochs": 0})
    assert inj.fired_log, "crash fault never fired"
    storage_url = cfg.config().get("checkpoint.storage-url")
    assert latest_complete_checkpoint(storage_url, job) == 1
    # the crashed incarnation provably spilled: run files on disk plus the
    # SPILL_STARTED event — both recorded at spill time, not through the
    # throttled gauge refresh a sub-second crash can outrun
    spill_dir = os.path.join(storage_url, job, "spill")
    runs_on_disk = [f for _d, _s, fs in os.walk(spill_dir) for f in fs
                    if f.startswith("run-")]
    assert runs_on_disk, "no spill runs were written before the crash"
    assert any(e["code"] == "SPILL_STARTED" for e in recorder.events(job))
    eng2 = ts.build(sql, 2, job, restore_epoch=1)
    eng2.run_to_completion(timeout=180)
    ts.assert_outputs("spill_keyspace", out)
    # the restored incarnation adopted the cold tier (its own byte counter
    # restarts at zero; cold partitions + probe traffic are the evidence)
    assert_spill_engaged(job, require_bytes=False)
    recorder.clear_job(job)


@pytest.mark.chaos
def test_chaos_spill_write_fail_mid_stream(tmp_path, _storage):
    """Storage failing every spill write from the 3rd on: partitions
    re-pin hot (SPILL_FALLBACK), the budget is overrun — degraded — and
    the output stays byte-exact."""
    ts = _smoke()
    cfg.update(SPILL_CFG)
    out = str(tmp_path / "out.json")
    job = "spill-chaos-wfail"
    inj = faults.install("spill_write:fail@after=3", seed=1337)
    try:
        eng = ts.build(ts.load_sql("spill_keyspace", out), 1, job)
        eng.run_to_completion(timeout=180)
    finally:
        faults.clear()
    assert inj.fired_log, "spill_write fault never fired"
    ts.assert_outputs("spill_keyspace", out)
    evs = recorder.events(job)
    assert any(e["code"] == "SPILL_FALLBACK" for e in evs)
    assert any(e["code"] == "SPILL_STARTED" for e in evs)
    recorder.clear_job(job)


@pytest.mark.chaos
def test_chaos_spill_probe_fail_recovers_in_place(tmp_path, _storage):
    ts = _smoke()
    cfg.update(SPILL_CFG)
    out = str(tmp_path / "out.json")
    job = "spill-chaos-pfail"
    inj = faults.install("spill_probe:fail_once@after=2", seed=1337)
    try:
        eng = ts.build(ts.load_sql("spill_keyspace", out), 1, job)
        eng.run_to_completion(timeout=180)
    finally:
        faults.clear()
    assert inj.fired_log, "spill_probe fault never fired"
    ts.assert_outputs("spill_keyspace", out)
    assert_spill_engaged(job)
    recorder.clear_job(job)


@pytest.mark.parametrize("family", ["updating_inner_join",
                                    "updating_full_join",
                                    "updating_inner_join_with_updating"])
def test_updating_join_families_spill_golden(family, tmp_path, _storage):
    """Join side stores through the tiered API: the updating-join smoke
    families run byte-exact with a budget small enough that side-store
    rows actually spill and promote back on match."""
    ts = _smoke()
    cfg.update({"state.spill.enabled": True,
                "state.spill.budget-bytes": 4096})
    out = str(tmp_path / "out.json")
    job = f"spill-{family}"
    eng = ts.build(ts.load_sql(family, out), 1, job)
    eng.run_to_completion(timeout=180)
    ts.assert_outputs(family, out)
    recorder.clear_job(job)


def test_updating_join_spill_restore_roundtrip(tmp_path, _storage):
    """Checkpoint/stop/restore of a spilling join: run manifests (with
    dead-row sets) rebuild the side-store tier byte-exactly."""
    ts = _smoke()
    cfg.update({"state.spill.enabled": True,
                "state.spill.budget-bytes": 4096,
                "testing.source-gate-epochs": 2})
    out = str(tmp_path / "out.json")
    job = "spill-join-restore"
    sql = ts.load_sql("updating_inner_join", out)
    eng = ts.build(sql, 2, job)
    eng.start()
    for ep in (1, 2):
        assert eng.checkpoint_and_wait(ep, timeout=60), f"epoch {ep}"
    eng.stop()
    eng.join(timeout=60)
    cfg.update({"testing.source-gate-epochs": 0})
    eng2 = ts.build(sql, 2, job, restore_epoch=2)
    eng2.run_to_completion(timeout=180)
    ts.assert_outputs("updating_inner_join", out)
    recorder.clear_job(job)
