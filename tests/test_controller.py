"""Control plane: FSM, schedulers, REST API, recovery.

Covers reference behaviors: job FSM transitions (states/mod.rs), embedded
scheduler runs (schedulers/embedded.rs), process-scheduler worker spawning +
crash recovery with restart budget (job_controller/mod.rs:504-530), stop with
final checkpoint + restart from it (states/scheduling.rs restore path), and
the REST resource model (arroyo-api/src/rest.rs).
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from arroyo_tpu.controller import ControllerServer, Database, JobState
from arroyo_tpu.controller.scheduler import EmbeddedScheduler, ProcessScheduler
from arroyo_tpu.controller.states import IllegalTransition, check_transition


SMOKE = os.path.join(os.path.dirname(__file__), "smoke")


def _sql(tmp_path, name="grouped_aggregates"):
    with open(os.path.join(SMOKE, "queries", f"{name}.sql")) as f:
        sql = f.read()
    out = str(tmp_path / "out.json")
    return sql.replace("$input_dir", os.path.join(SMOKE, "inputs")).replace(
        "$output_path", out
    ), out


def _assert_golden(out, name="grouped_aggregates"):
    import glob

    got = []
    for p in sorted(glob.glob(out) + glob.glob(out + ".*")):
        with open(p) as f:
            got.extend(json.loads(l) for l in f if l.strip())
    with open(os.path.join(SMOKE, "golden", f"{name}.json")) as f:
        want = [json.loads(l) for l in f if l.strip()]
    key = lambda r: json.dumps(r, sort_keys=True)
    assert sorted(map(key, got)) == sorted(map(key, want))


def test_fsm_transitions():
    check_transition(JobState.CREATED, JobState.COMPILING)
    check_transition(JobState.RUNNING, JobState.RECOVERING)
    check_transition(JobState.CHECKPOINT_STOPPING, JobState.STOPPING)
    with pytest.raises(IllegalTransition):
        check_transition(JobState.CREATED, JobState.RUNNING)
    with pytest.raises(IllegalTransition):
        check_transition(JobState.FINISHED, JobState.RUNNING)


def test_embedded_job_to_finished(tmp_path, _storage):
    sql, out = _sql(tmp_path)
    db = Database()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("agg", sql, 1)
        jid = db.create_job(pid)
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        _assert_golden(out)
    finally:
        ctl.stop()


def test_stop_with_checkpoint_and_restart(tmp_path, _storage):
    from arroyo_tpu import config as cfg

    sql, out = _sql(tmp_path)
    db = Database()
    cfg.update({"testing.source-read-delay-micros": 4000,
                "checkpoint.interval-ms": 150})
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        time.sleep(0.4)  # let at least one periodic checkpoint land
        db.update_job(jid, desired_stop="checkpoint")
        state = ctl.wait_for_state(jid, "Stopped", timeout=60)
        assert state == "Stopped"
        epochs = [c for c in db.list_checkpoints(jid) if c["state"] == "complete"]
        assert epochs, "stop-with-checkpoint must record a completed epoch"
        # restart: resumes from the stop checkpoint and finishes
        cfg.update({"testing.source-read-delay-micros": 0})
        db.update_job(jid, desired_stop=None, state="Restarting")
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        _assert_golden(out)
    finally:
        cfg.update({"testing.source-read-delay-micros": 0,
                    "checkpoint.interval-ms": 10_000})
        ctl.stop()


def test_process_scheduler_crash_recovery(tmp_path, _storage):
    """Kill the worker mid-run; controller must restore from the last
    checkpoint and produce exactly-once output."""
    from arroyo_tpu import config as cfg

    sql, out = _sql(tmp_path)
    db = Database()
    # subprocess workers read config from the environment
    os.environ["ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS"] = "3000"
    os.environ["ARROYO_TPU__CHECKPOINT__STORAGE_URL"] = cfg.config().get(
        "checkpoint.storage-url"
    )
    cfg.update({"checkpoint.interval-ms": 150})
    ctl = ControllerServer(db, ProcessScheduler()).start()
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        # wait for a completed checkpoint, then kill the worker process
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(c["state"] == "complete" for c in db.list_checkpoints(jid)):
                break
            time.sleep(0.05)
        jc = ctl.jobs[jid]
        assert jc.handle is not None
        jc.handle.proc.kill()
        os.environ["ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS"] = "0"
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        job = db.get_job(jid)
        assert job["restarts"] >= 1
        _assert_golden(out)
    finally:
        os.environ.pop("ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS", None)
        os.environ.pop("ARROYO_TPU__CHECKPOINT__STORAGE_URL", None)
        cfg.update({"checkpoint.interval-ms": 10_000})
        ctl.stop()


def test_live_rescale_midstream(tmp_path, _storage):
    """PATCH parallelism on a running job: controller drains the worker
    behind a final checkpoint (Running -> Rescaling), reschedules at the
    new parallelism restoring from it, and output parity holds
    (reference states/rescaling.rs:1-70 + jobs.rs parallelism patch)."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.api import ApiServer

    sql, out = _sql(tmp_path)
    db = Database()
    cfg.update({"testing.source-read-delay-micros": 4000,
                "checkpoint.interval-ms": 150})
    api = ApiServer(db, port=0).start()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()

    def req(method, path, body=None):
        data = json.dumps(body).encode() if body is not None else None
        r = urllib.request.Request(
            f"http://127.0.0.1:{api.port}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(r) as resp:
            return json.loads(resp.read())

    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        time.sleep(0.3)  # let some input flow at p=2
        resp = req("PATCH", f"/api/v1/jobs/{jid}", {"parallelism": 3})
        assert resp["desired_parallelism"] == 3
        # the job must pass through Rescaling on its way back to Running
        seen = set()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            seen.add(db.get_job(jid)["state"])
            if "Rescaling" in seen and db.get_job(jid)["state"] == "Running":
                break
            time.sleep(0.01)
        assert "Rescaling" in seen, f"states seen: {seen}"
        assert ctl.jobs[jid].parallelism == 3
        # the rescale restored from the drain checkpoint, not from scratch
        assert ctl.jobs[jid].restore_epoch is not None
        cfg.update({"testing.source-read-delay-micros": 0})
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        _assert_golden(out)
        # the new scale is persisted for future restarts
        assert db.get_pipeline(pid)["parallelism"] == 3
        assert db.get_job(jid)["desired_parallelism"] is None
        # rescaling a terminal job is rejected
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("PATCH", f"/api/v1/jobs/{jid}", {"parallelism": 2})
        assert ei.value.code == 409
    finally:
        cfg.update({"testing.source-read-delay-micros": 0,
                    "checkpoint.interval-ms": 10_000})
        ctl.stop()
        api.stop()


def test_subsume_torn_epoch_refuses_complete_epochs(tmp_path, _storage):
    """The stuck-checkpoint watchdog's cleanup may only delete epochs that
    never went globally durable (no job-level metadata marker)."""
    import os as _os

    from arroyo_tpu.state.tables import (
        checkpoint_dir,
        subsume_torn_epoch,
        write_job_checkpoint_metadata,
    )

    url = _storage
    # epoch 1: complete (marker present) -> refused
    write_job_checkpoint_metadata(url, "j1", 1, {"operators": []})
    assert subsume_torn_epoch(url, "j1", 1) is False
    assert _os.path.isdir(checkpoint_dir(url, "j1", 1))
    # epoch 2: torn (shards, no marker) -> subsumed
    _os.makedirs(_os.path.join(checkpoint_dir(url, "j1", 2), "operator-x"))
    assert subsume_torn_epoch(url, "j1", 2) is True
    assert not _os.path.isdir(checkpoint_dir(url, "j1", 2))
    # epoch 3: nothing on disk -> no-op
    assert subsume_torn_epoch(url, "j1", 3) is False


def test_stuck_checkpoint_watchdog_subsume_retry_recover(tmp_path, _storage):
    """A subtask hangs mid-epoch-2-snapshot: the checkpoint.timeout-ms
    watchdog must declare the epoch failed (db record), subsume its torn
    shards, retry at a fresh epoch, and — after max-consecutive-failures —
    restore the whole worker set from the last globally complete
    checkpoint, finishing with golden output."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults

    sql, out = _sql(tmp_path)
    db = Database()
    cfg.update({
        "controller.workers-per-job": 2,
        "checkpoint.interval-ms": 100,
        "checkpoint.timeout-ms": 400,
        "checkpoint.max-consecutive-failures": 2,
        # only the watchdog may fire here, not heartbeat detection
        "pipeline.worker-heartbeat-timeout-ms": 60_000,
        "testing.source-read-delay-micros": 4000,
    })
    faults.install("worker:hang=6@barrier=2&step=1", seed=7)
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        jc = ctl.jobs[jid]
        # watchdog fired: some epoch was declared failed and subsumed
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if any(c["state"] == "failed" for c in db.list_checkpoints(jid)):
                break
            time.sleep(0.05)
        assert any(c["state"] == "failed" for c in db.list_checkpoints(jid)), (
            "stuck epoch was never declared failed")
        # escalation: K consecutive wedges -> whole-set restore
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        job = db.get_job(jid)
        assert int(job["restarts"]) >= 1, "wedged set was never restored"
        assert jc.watchdog_failed_epochs >= 2
        assert jc.watchdog_escalations >= 1, (
            "K consecutive wedges never escalated to a whole-set restore")
        # trace-backed wedge diagnostic: the escalation report attaches the
        # epoch timeline and names the exact stuck subtask ("node/sub:
        # snapshot started, never acked" / "barrier never arrived"); the
        # tail survives the failure-message truncation by construction
        import re as _re

        msg = job["failure_message"] or ""
        assert _re.search(
            r"\S+/\d+: (snapshot started, never acked|"
            r"barrier never arrived|aligning)", msg), msg
        # epoch timelines are queryable postmortem from the controller DB
        assert db.list_traces(jid)
        _assert_golden(out)
    finally:
        faults.clear()
        cfg.update({"controller.workers-per-job": 1,
                    "checkpoint.interval-ms": 10_000,
                    "checkpoint.timeout-ms": 600_000,
                    "checkpoint.max-consecutive-failures": 3,
                    "testing.source-read-delay-micros": 0})
        ctl.stop()


def test_embedded_hung_worker_heartbeat_detected(tmp_path, _storage):
    """EmbeddedWorkerHandle.last_heartbeat derives from actual engine
    progress (task run-loop beats), so an engine wedged inside a snapshot
    trips the controller's heartbeat timeout even though its threads still
    exist; the job recovers from the last complete checkpoint."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults

    sql, out = _sql(tmp_path, name="select_star")
    db = Database()
    cfg.update({
        "checkpoint.interval-ms": 100,
        "pipeline.worker-heartbeat-timeout-ms": 2500,
        "testing.source-read-delay-micros": 4000,
    })
    # epoch 1 completes; the first subtask into epoch 2's snapshot wedges
    # far longer than the heartbeat timeout
    faults.install("worker:hang=12@barrier=2&step=1", seed=7)
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("sel", sql, 2)
        jid = db.create_job(pid)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            job = db.get_job(jid)
            if job and int(job["restarts"] or 0) >= 1:
                break
            time.sleep(0.05)
        job = db.get_job(jid)
        assert int(job["restarts"] or 0) >= 1, "hung embedded worker never detected"
        assert "heartbeat" in (job["failure_message"] or "")
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        _assert_golden(out, name="select_star")
    finally:
        faults.clear()
        cfg.update({"checkpoint.interval-ms": 10_000,
                    "pipeline.worker-heartbeat-timeout-ms": 30_000,
                    "testing.source-read-delay-micros": 0})
        ctl.stop()


def test_controller_checkpoint_gc(tmp_path, _storage):
    """checkpoint.compaction.epochs drives controller-side GC: every K
    completed epochs the newest complete one is compacted and older epochs
    dropped — never past the newest complete epoch, never the "final"
    drained-source snapshots."""
    import os as _os

    from arroyo_tpu import config as cfg

    sql, out = _sql(tmp_path)
    db = Database()
    cfg.update({
        "checkpoint.interval-ms": 100,
        "checkpoint.compaction.epochs": 2,
        "testing.source-read-delay-micros": 4000,
    })
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        # GC runs on a background thread; give the last round a moment
        deadline = time.monotonic() + 15
        compacted: list = []
        while time.monotonic() < deadline and not compacted:
            cks = db.list_checkpoints(jid)
            compacted = [c["epoch"] for c in cks if c["state"] == "compacted"]
            if not compacted:
                time.sleep(0.1)
        assert compacted, f"GC never ran: {cks}"
        base = _os.path.join(_storage, jid, "checkpoints")
        remaining = sorted(
            int(fn.split("-")[1]) for fn in _os.listdir(base)
            if fn.startswith("checkpoint-") and fn.split("-")[1].isdigit())
        # everything older than the newest compacted epoch was dropped
        assert remaining and min(remaining) >= max(compacted), (
            f"GC left epochs {remaining} older than compacted {compacted}")
        assert _os.path.isdir(_os.path.join(base, "checkpoint-final")), (
            "GC must never delete the final drained-source snapshots")
        _assert_golden(out)
    finally:
        cfg.update({"checkpoint.interval-ms": 10_000,
                    "checkpoint.compaction.epochs": 0,
                    "testing.source-read-delay-micros": 0})
        ctl.stop()


def test_multi_worker_rescale(tmp_path, _storage):
    """Rescaling a 2-worker job: the whole set drains behind one stopping
    checkpoint (globally durable via the coordinator), then reschedules at
    the new parallelism — still 2 workers — restoring from it."""
    from arroyo_tpu import config as cfg

    sql, out = _sql(tmp_path)
    db = Database()
    cfg.update({
        "controller.workers-per-job": 2,
        "checkpoint.interval-ms": 150,
        "testing.source-read-delay-micros": 4000,
    })
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        time.sleep(0.3)  # let some input flow at p=2
        db.update_job(jid, desired_parallelism=3)
        seen = set()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            seen.add(db.get_job(jid)["state"])
            if "Rescaling" in seen and db.get_job(jid)["state"] == "Running":
                break
            time.sleep(0.01)
        assert "Rescaling" in seen, f"states seen: {seen}"
        jc = ctl.jobs[jid]
        assert jc.parallelism == 3
        # the rescale restored from the drain checkpoint, not from scratch
        assert jc.restore_epoch is not None
        cfg.update({"testing.source-read-delay-micros": 0})
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        job = db.get_job(jid)
        assert int(job["n_workers"]) == 2
        assert db.get_pipeline(pid)["parallelism"] == 3
        _assert_golden(out)
    finally:
        cfg.update({"controller.workers-per-job": 1,
                    "checkpoint.interval-ms": 10_000,
                    "testing.source-read-delay-micros": 0})
        ctl.stop()


def test_process_scheduler_two_worker_set(tmp_path, _storage):
    """Full multi-process worker set: N subprocesses exchange data-plane
    peers through the controller, relay per-subtask acks over the wire
    protocol, and only complete epochs on controller-injected commits."""
    from arroyo_tpu import config as cfg

    sql, out = _sql(tmp_path)
    db = Database()
    os.environ["ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS"] = "8000"
    os.environ["ARROYO_TPU__CHECKPOINT__STORAGE_URL"] = cfg.config().get(
        "checkpoint.storage-url")
    cfg.update({"controller.workers-per-job": 2,
                "checkpoint.interval-ms": 300})
    ctl = ControllerServer(db, ProcessScheduler()).start()
    try:
        pid = db.create_pipeline("agg", sql, 2)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=120)
        jc = ctl.jobs[jid]
        assert len(jc.handles) == 2
        state = ctl.wait_for_state(jid, "Finished", timeout=180)
        assert state == "Finished"
        job = db.get_job(jid)
        assert int(job["n_workers"]) == 2
        # the coordinator (not any worker) recorded globally durable epochs
        assert any(c["state"] == "complete" for c in db.list_checkpoints(jid))
        assert jc.checkpoint_event_log, "no coordinated checkpoints happened"
        # multi-worker metrics aggregation: the controller snapshot merges
        # BOTH subprocesses' registries (union by subtask label) instead of
        # one worker's report overwriting the other's operators
        snap = db.get_metrics(jid) or {}
        labels = {(op, sub) for op, m in snap.items() if isinstance(m, dict)
                  for sub in m.get("per_subtask", {})}
        assert any(
            {(op, "0"), (op, "1")} <= labels for op, _ in labels
        ), f"no operator carries both workers' subtask labels: {sorted(labels)}"
        # merged /profile view (ISSUE 7): the persisted cost profile carries
        # BOTH workers' subtasks per operator with attributed self-time, and
        # the EXPLAIN ANALYZE renderer annotates the plan from it
        prof = db.get_profile(jid) or {}
        prof_labels = {(op, sub) for op, p in prof.items()
                       for sub in p.get("per_subtask", {})}
        two_sided = [op for op, _ in prof_labels
                     if {(op, "0"), (op, "1")} <= prof_labels]
        assert two_sided, (
            f"merged profile lacks a both-workers operator: {sorted(prof_labels)}")
        assert any(sum((p.get("self_time") or {}).values()) > 0
                   for p in prof.values()), "profile has no attributed self-time"
        from arroyo_tpu.obs.profile import render_explain

        text = render_explain([], [], prof, db.get_job(jid))
        assert f"EXPLAIN ANALYZE job {jid}" in text and "busy" in text
        # the workers relayed their epoch span events; the controller
        # persisted whole-job trace timelines with both workers' acks
        traces = db.list_traces(jid)
        assert traces, "no epoch traces persisted to the controller DB"
        ack_subs = {(e["node"], e["subtask"]) for t in traces
                    for e in t["events"] if e["event"] == "ack"}
        assert len(ack_subs) >= 2, ack_subs
        _assert_golden(out)
    finally:
        os.environ.pop("ARROYO_TPU__TESTING__SOURCE_READ_DELAY_MICROS", None)
        os.environ.pop("ARROYO_TPU__CHECKPOINT__STORAGE_URL", None)
        cfg.update({"controller.workers-per-job": 1,
                    "checkpoint.interval-ms": 10_000})
        ctl.stop()


def test_rest_api_lifecycle(tmp_path, _storage):
    from arroyo_tpu.api import ApiServer

    sql, out = _sql(tmp_path)
    db = Database()
    api = ApiServer(db, port=0).start()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()

    def req(method, path, body=None):
        data = json.dumps(body).encode() if body is not None else None
        r = urllib.request.Request(
            f"http://127.0.0.1:{api.port}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(r) as resp:
            return json.loads(resp.read())

    try:
        assert req("GET", "/api/v1/ping")["pong"]
        bad = req("POST", "/api/v1/pipelines/validate", {"query": "SELEC nope"})
        assert not bad["valid"] and bad["errors"]
        ok = req("POST", "/api/v1/pipelines/validate", {"query": sql})
        assert ok["valid"]
        created = req("POST", "/api/v1/pipelines", {"name": "agg", "query": sql})
        jid = created["job_id"]
        assert any(p["id"] == created["id"] for p in req("GET", "/api/v1/pipelines")["data"])
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        assert req("GET", f"/api/v1/jobs/{jid}")["state"] == "Finished"
        _assert_golden(out)
        assert req("DELETE", f"/api/v1/pipelines/{created['id']}")["deleted"]
    finally:
        ctl.stop()
        api.stop()
