"""Runtime cost attribution (ISSUE 7): per-operator self-time, state-size
gauges, key-skew sketches, the /profile snapshot, and EXPLAIN ANALYZE.

Covers the determinism contract (identical replays — and checkpoint/restore
replays — rebuild identical sketch summaries), state-gauge accuracy against
``total_rows()`` ground truth, late-row export, and the profile export/merge
path shared by single- and multi-worker jobs. The 2-worker merged /profile
assertion lives with the process-scheduler set test in test_controller.py;
the <5% overhead guard lives in test_perf_guard.py (slow).
"""

from __future__ import annotations

import json
import time

import numpy as np
import pytest

import arroyo_tpu
from arroyo_tpu.batch import TIMESTAMP_FIELD, Batch
from arroyo_tpu.metrics import registry
from arroyo_tpu.obs.sketch import KeySketch, merge_topk


# ------------------------------------------------------------- sketch unit


def test_sketch_batch_boundary_invariance():
    """sample_every=1 counts rows exactly, so ANY re-batching of the same
    row stream (what coalescing does under timing jitter) yields the same
    summary — the replay-determinism foundation."""
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 50, size=10_000, dtype=np.uint64)
    a, b = KeySketch(capacity=64), KeySketch(capacity=64)
    a.observe(keys)
    for lo in range(0, len(keys), 137):
        b.observe(keys[lo:lo + 137])
    assert a.topk(10) == b.topk(10)
    assert a.total == b.total == 10_000


def test_sketch_heavy_hitter_detection_under_eviction():
    """A Zipf-ish hot key survives eviction pressure (capacity << keyspace)
    and its count-error lower bound stays a true floor."""
    rng = np.random.default_rng(1)
    cold = rng.integers(1000, 100_000, size=20_000, dtype=np.uint64)
    hot = np.full(5_000, 42, dtype=np.uint64)
    mixed = np.concatenate([cold, hot])
    rng.shuffle(mixed)
    sk = KeySketch(capacity=32)
    for lo in range(0, len(mixed), 997):
        sk.observe(mixed[lo:lo + 997])
    top = sk.topk(1)[0]
    assert top["key"] == 42
    assert top["count"] - top["error"] <= 5_000 <= top["count"]
    assert top["share"] == pytest.approx(5_000 / 25_000, abs=0.05)


def test_sketch_state_roundtrip_and_merge():
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 30, size=5_000, dtype=np.uint64)
    orig = KeySketch(capacity=64)
    orig.observe(keys)
    restored = KeySketch(capacity=64)
    restored.merge_state(json.loads(json.dumps(orig.state())))  # DB roundtrip
    assert restored.topk(10) == orig.topk(10)
    assert restored.total == orig.total
    # rescale-style fold of two disjoint summaries never under-counts
    s1, s2 = KeySketch(capacity=8), KeySketch(capacity=8)
    s1.observe(np.full(100, 7, dtype=np.uint64))
    s2.observe(np.full(50, 7, dtype=np.uint64))
    s1.merge_state(s2.state())
    assert s1.topk(1)[0]["count"] >= 150


def test_merge_topk_across_subtasks():
    t1 = [{"key": "00000000000000aa", "count": 100, "error": 0, "share": 0.5}]
    t2 = [{"key": "00000000000000aa", "count": 60, "error": 5, "share": 0.3},
          {"key": "00000000000000bb", "count": 40, "error": 0, "share": 0.2}]
    merged = merge_topk([t1, t2], total=400, k=2)
    assert merged[0] == {"key": "00000000000000aa", "count": 160,
                         "error": 5, "share": 0.4}
    assert merged[1]["key"] == "00000000000000bb"


# -------------------------------------------------------- engine integration


def _keyed_sql(tmp_path, n=4000, keys=7):
    src = tmp_path / "in.json"
    with open(src, "w") as f:
        for i in range(n):
            f.write(json.dumps({"k": f"u{i % keys}", "x": i,
                                "_timestamp": i * 1000}) + "\n")
    return f"""
    CREATE TABLE t (k TEXT, x BIGINT) WITH (connector='single_file',
      path='{src}', format='json', type='source');
    SELECT k, count(*) AS n, tumble(interval '1 second') AS w
    FROM t GROUP BY k, w;
    """


def _run_sql(sql, job_id):
    from arroyo_tpu.engine.engine import run_graph
    from arroyo_tpu.sql import plan_query

    arroyo_tpu._load_operators()
    pp = plan_query(sql)
    run_graph(pp.graph, job_id=job_id, timeout=120)
    return pp


def _agg_entry(jm):
    op = next(o for o in jm if "tumbling" in o or "agg" in o)
    return op, jm[op]


def test_self_time_state_and_sketch_export(tmp_path, _storage):
    registry.clear_job("prof-export")
    sql = _keyed_sql(tmp_path)
    pp = _run_sql(sql, "prof-export")
    jm = registry.job_metrics("prof-export")
    op, agg = _agg_entry(jm)
    # self-time attributed, busy% and cost-per-row derived at export
    assert agg["self_time"]["process"] > 0
    assert agg["busy_pct"] > 0
    assert agg["self_us_per_row"] > 0
    per = agg["per_subtask"]["0"]
    assert set(per["self_time"]) == {"process", "tick", "close", "checkpoint"}
    # the keyed insert path fed the sketch: 7 uniform keys at ~1/7 share
    hot = agg["hot_keys"]
    assert len(hot) >= 5
    assert all(len(e["key"]) == 16 for e in hot)  # fixed-width hex
    assert hot[0]["share"] == pytest.approx(1 / 7, abs=0.02)
    # prometheus exposition carries the new families
    text = registry.prometheus_text()
    assert f'arroyo_worker_self_time_seconds{{job="prof-export",operator="{op}"' \
           in text
    assert "# TYPE arroyo_state_rows gauge" in text
    # sinks/sources without state report no state tables; the watermark
    # operator's global table rides the gauges
    wm_op = next(o for o in jm if "watermark" in o)
    assert "s" in jm[wm_op]["state_rows"]


def test_sketch_identical_across_replays(tmp_path, _storage):
    """Two identical runs (fresh registry each) export identical hot-key
    summaries — seeded, no randomness, row-exact counting."""
    sql = _keyed_sql(tmp_path)
    tops = []
    for run in range(2):
        registry.clear_job("prof-replay")
        _run_sql(sql, "prof-replay")
        _op, agg = _agg_entry(registry.job_metrics("prof-replay"))
        tops.append(agg["hot_keys"])
        assert agg["sketch_total"] > 0
    assert tops[0] == tops[1]


def test_sketch_checkpoint_restore_continuity(tmp_path, _storage):
    """A run that checkpoints mid-stream and a restored run that finishes
    the stream rebuild the same summary an uninterrupted run produces:
    the __sketch table restores the exact space-saving state + sampling
    phase. Drives the engine directly so the checkpoint lands at a
    deterministic row boundary."""
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.sql import plan_query

    arroyo_tpu._load_operators()
    from arroyo_tpu import config as cfg

    cfg.update({"testing.source-read-delay-micros": 2000})
    sql = _keyed_sql(tmp_path, n=3000)

    registry.clear_job("prof-ckpt")
    pp = plan_query(sql)
    eng = Engine(pp.graph, job_id="prof-ckpt")
    eng.start()
    assert eng.checkpoint_and_wait(1, timeout=60)
    eng.join(120)
    _op, agg = _agg_entry(registry.job_metrics("prof-ckpt"))
    uninterrupted = agg["hot_keys"]

    # restore from epoch 1: replays the remainder; fresh sketch merges the
    # checkpointed state, so the final summary matches the full run
    registry.clear_job("prof-ckpt")
    pp2 = plan_query(sql)
    eng2 = Engine(pp2.graph, job_id="prof-ckpt", restore_epoch=1)
    eng2.run_to_completion(120)
    _op, agg2 = _agg_entry(registry.job_metrics("prof-ckpt"))
    assert agg2["hot_keys"] == uninterrupted
    cfg.update({"testing.source-read-delay-micros": 0})


def test_state_gauges_match_total_rows_ground_truth(_storage, tmp_path):
    """Profiler refresh vs the tables' own accounting."""
    from arroyo_tpu.obs.profile import TaskProfiler
    from arroyo_tpu.operators.base import Operator
    from arroyo_tpu.state.tables import TableManager
    from arroyo_tpu.types import TaskInfo

    ti = TaskInfo("gauge-job", "op", "value", 0, 1)
    tm = TableManager(ti, str(tmp_path / "ck"))
    exp = tm.expiring_time_key("t", retention_micros=10**9)
    exp.insert(Batch({TIMESTAMP_FIELD: np.arange(500, dtype=np.int64),
                      "x": np.arange(500, dtype=np.int64)}))
    exp.insert(Batch({TIMESTAMP_FIELD: np.arange(250, dtype=np.int64),
                      "x": np.arange(250, dtype=np.int64)}))
    g = tm.global_keyed("offsets")
    for i in range(10):
        g.insert(i, {"pos": i})
    registry.clear_job("gauge-job")
    m = registry.task("gauge-job", "op", 0)
    prof = TaskProfiler(m, Operator(), tm)
    prof.refresh(force=True)
    assert m.state_rows["t"] == exp.total_rows() == 750
    assert m.state_bytes["t"] == sum(b.nbytes() for b in exp.batches) > 0
    assert m.state_rows["offsets"] == 10
    assert m.state_bytes["offsets"] > 0
    registry.clear_job("gauge-job")


def test_join_side_store_gauges_and_expiry_late_rows(_storage):
    """The updating join reports LIVE _SideStore sizes (overriding the
    barrier-time host tables) and counts TTL-expired drops as late rows."""
    from arroyo_tpu.operators.joins import JoinWithExpiration
    from arroyo_tpu.types import Watermark

    op = JoinWithExpiration({
        "join_type": "inner",
        "left_names": [("lx", "lx")], "right_names": [("rx", "rx")],
        "ttl_micros": 1000,
    })
    keys = np.arange(100, dtype=np.uint64)
    op.stores[0].append(keys.view(np.int64),
                        np.zeros(100, dtype=np.int64),
                        [np.arange(100).astype(object)],
                        np.zeros(100, dtype=np.int64), False)
    sizes = op.state_sizes()
    assert sizes["left"][0] == 100 and sizes["left"][1] > 0
    assert sizes["right"][0] == 0
    # watermark far past TTL expires everything buffered -> late_rows
    out = op.handle_watermark(Watermark.event_time(10_000), None, None)
    assert out is not None
    assert op.late_rows == 100
    assert op.state_sizes()["left"][0] == 0


def test_chained_operator_aggregates_members(_storage):
    from arroyo_tpu.operators.chained import ChainedOperator

    class _M:
        late_rows = 3

        def state_sizes(self):
            return {"t": (5, 80)}

    chain = ChainedOperator.__new__(ChainedOperator)
    chain.members = [_M(), _M()]
    assert chain.late_rows == 6
    assert chain.state_sizes() == {"c0.t": (5, 80), "c1.t": (5, 80)}


def test_late_rows_exported_from_window_operator(tmp_path, _storage):
    """Rows behind an emitted window drop AND surface as
    arroyo_late_rows_total — counting only, goldens untouched."""
    src = tmp_path / "in.json"
    with open(src, "w") as f:
        # ride event time far ahead, then inject stragglers behind the
        # closed windows (watermark interval defaults: every row advances)
        for i in range(2000):
            f.write(json.dumps({"k": "a", "x": i,
                                "_timestamp": i * 10_000}) + "\n")
        for i in range(50):
            f.write(json.dumps({"k": "a", "x": i, "_timestamp": 0}) + "\n")
    sql = f"""
    CREATE TABLE t (k TEXT, x BIGINT) WITH (connector='single_file',
      path='{src}', format='json', type='source');
    SELECT k, count(*) AS n, tumble(interval '1 second') AS w
    FROM t GROUP BY k, w;
    """
    registry.clear_job("prof-late")
    _run_sql(sql, "prof-late")
    jm = registry.job_metrics("prof-late")
    _op, agg = _agg_entry(jm)
    assert agg["late_rows"] == 50
    assert 'arroyo_late_rows_total{job="prof-late"' in registry.prometheus_text()


# ------------------------------------------------------ profile + explain


def test_job_profile_and_render_explain(tmp_path, _storage):
    from arroyo_tpu.obs.profile import job_profile, render_explain

    registry.clear_job("prof-view")
    sql = _keyed_sql(tmp_path)
    pp = _run_sql(sql, "prof-view")
    prof = job_profile(registry.job_metrics("prof-view"))
    op = next(o for o in prof if "tumbling" in o or "agg" in o)
    assert prof[op]["busy_pct"] > 0
    assert prof[op]["hot_keys"]
    assert "0" in prof[op]["per_subtask"]
    nodes = [{"id": n.node_id, "op": n.op.value,
              "description": n.description or n.op.value,
              "parallelism": n.parallelism} for n in pp.graph.nodes.values()]
    edges = [{"src": e.src, "dst": e.dst} for e in pp.graph.edges]
    text = render_explain(nodes, edges, prof,
                          {"id": "prof-view", "state": "Finished"})
    assert "EXPLAIN ANALYZE job prof-view" in text
    # sink-first plan, every operator present, annotated
    assert text.index("sink") < text.index("source")
    for nid in pp.graph.nodes:
        assert nid in text
    assert "busy" in text and "hot keys:" in text and "state:" in text
    registry.clear_job("prof-view")


def test_profile_api_endpoint_embedded(tmp_path, _storage, capsys):
    """GET /api/v1/jobs/<id>/profile serves the controller-persisted
    snapshot, and `python -m arroyo_tpu explain --api` renders the plan
    annotated from it."""
    import urllib.request

    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler

    arroyo_tpu._load_operators()
    db = Database()
    api = ApiServer(db, port=0).start()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    try:
        sql = _keyed_sql(tmp_path)
        pid = db.create_pipeline("prof", sql, 1)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Finished", timeout=120)

        def fetch():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{api.port}/api/v1/jobs/{jid}/profile",
                    timeout=10) as r:
                return json.load(r)["data"]

        # the controller's terminal tick flushes the final registry snapshot
        # right after the state flip; poll past that race
        deadline = time.monotonic() + 10
        prof = fetch()
        while time.monotonic() < deadline:
            ops = [o for o in (prof or {}) if "tumbling" in o or "agg" in o]
            if ops and prof[ops[0]]["self_time"]["process"] > 0:
                break
            time.sleep(0.1)
            prof = fetch()
        assert prof, "no profile served"
        op = next(o for o in prof if "tumbling" in o or "agg" in o)
        assert prof[op]["self_time"]["process"] > 0
        assert db.get_profile(jid) is not None
        # the full CLI path: plan via /pipelines/<id>/graph, numbers via
        # /profile, rendered sink-first with annotations
        from arroyo_tpu.cli import main as cli_main

        rc = cli_main(["explain", jid, "--api",
                       f"http://127.0.0.1:{api.port}"])
        out = capsys.readouterr().out
        assert rc == 0
        assert f"EXPLAIN ANALYZE job {jid}" in out
        assert op in out and "busy" in out and "hot keys:" in out
    finally:
        ctl.stop()
        api.stop()


def test_profile_disabled_zero_surface(tmp_path, _storage):
    """profile.enabled=false: no sketch, no self-time, run still correct."""
    from arroyo_tpu import config as cfg

    cfg.update({"profile.enabled": False})
    try:
        registry.clear_job("prof-off")
        sql = _keyed_sql(tmp_path, n=500)
        _run_sql(sql, "prof-off")
        jm = registry.job_metrics("prof-off")
        _op, agg = _agg_entry(jm)
        assert sum(agg["self_time"].values()) == 0
        assert "hot_keys" not in agg
        assert agg["busy_pct"] == 0
    finally:
        cfg.update({"profile.enabled": True})
        registry.clear_job("prof-off")