"""Micro-batch coalescing correctness suite (ISSUE 5).

The coalescing layer may only change BATCH BOUNDARIES, never content or
signal ordering: goldens must stay byte-exact with coalescing on/off at any
row/byte/delay setting, signals must flush pending rows ahead of themselves,
checkpoint/restore must stay exact with rows buffered mid-stream, and the
fused multi-window join close must emit exactly the per-window groups.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from arroyo_tpu.batch import TIMESTAMP_FIELD, Batch
from arroyo_tpu.types import Signal, SignalKind, Watermark

from test_smoke import assert_outputs, build, load_sql


# ------------------------------------------------------------- unit layer


class RecordingDest:
    """Duck-types TaskInbox.put; remembers arrival order."""

    def __init__(self):
        self.items = []

    def put(self, input_index, item):
        self.items.append(item)


def make_collector(**over):
    from arroyo_tpu import config as cfg
    from arroyo_tpu.graph import EdgeType
    from arroyo_tpu.operators.collector import Collector, OutEdge

    cfg.update({f"engine.coalesce.{k}": v for k, v in over.items()})
    dest = RecordingDest()
    col = Collector([OutEdge(EdgeType.FORWARD, [dest], [0])], 0)
    return col, dest


def b(n: int, base: int = 0) -> Batch:
    return Batch({
        "x": np.arange(base, base + n, dtype=np.int64),
        TIMESTAMP_FIELD: np.full(n, 1000, dtype=np.int64),
    })


def test_signal_flushes_pending_rows_first(_storage):
    col, dest = make_collector(**{"max-rows": 1000, "max-delay-ms": 10_000})
    col.collect(b(3))
    col.collect(b(2, base=3))
    assert dest.items == []  # buffered: below every threshold
    col.broadcast(Signal.watermark_of(Watermark.event_time(5)))
    assert len(dest.items) == 2
    assert isinstance(dest.items[0], Batch)  # rows precede the signal
    assert dest.items[0].num_rows == 5
    assert np.array_equal(dest.items[0]["x"], np.arange(5))
    assert isinstance(dest.items[1], Signal)
    assert dest.items[1].kind == SignalKind.WATERMARK


def test_row_threshold_flush_and_big_batch_passthrough(_storage):
    col, dest = make_collector(**{"max-rows": 4})
    col.collect(b(2))
    col.collect(b(2, base=2))
    assert len(dest.items) == 1 and dest.items[0].num_rows == 4
    big = b(100)
    col.collect(big)  # >= max-rows with nothing pending: no copy at all
    assert dest.items[1] is big


def test_schema_change_flushes_before_concat(_storage):
    col, dest = make_collector(**{"max-rows": 1000, "max-delay-ms": 10_000})
    col.collect(b(2))
    other = Batch({"y": np.ones(3), TIMESTAMP_FIELD: np.zeros(3, dtype=np.int64)})
    col.collect(other)
    assert len(dest.items) == 1 and "x" in dest.items[0]
    col.flush()
    assert len(dest.items) == 2 and "y" in dest.items[1]


def test_time_based_flush(_storage):
    col, dest = make_collector(**{"max-rows": 1000, "max-delay-ms": 5})
    col.collect(b(2))
    col.flush_expired(col._pending_since + 0.001)
    assert dest.items == []  # not expired yet
    col.flush_expired(col._pending_since + 0.006)
    assert len(dest.items) == 1 and dest.items[0].num_rows == 2


def test_coalescing_disabled_is_passthrough(_storage):
    col, dest = make_collector(enabled=False)
    small = b(1)
    col.collect(small)
    assert dest.items == [small]


def test_emit_and_transit_histograms_exported(_storage):
    from arroyo_tpu.engine.queues import TaskInbox
    from arroyo_tpu.metrics import registry

    col, dest = make_collector(**{"max-rows": 4})
    tm = registry.task("co-job", "op", 0)
    col.metrics = tm
    col.collect(b(5))
    assert tm.emit_batch_rows.count == 1 and tm.emit_batch_rows.sum == 5
    inbox = TaskInbox(1, 100)
    inbox.metrics = tm
    inbox.put(0, b(3))
    inbox.get(timeout=1)
    assert tm.queue_transit.count == 1
    text = registry.prometheus_text()
    assert "arroyo_worker_emit_batch_rows_bucket" in text
    assert "arroyo_worker_queue_transit_seconds_count" in text
    registry.clear_job("co-job")


# ------------------------------------------------- golden on/off equivalence

COALESCE_FAMILIES = ["tumbling_aggregates", "sliding_window", "updating_aggregate"]
SETTINGS = [
    pytest.param({"enabled": False}, id="off"),
    # everything buffers until a signal: the pure ordering-correctness axis
    pytest.param({"max-rows": 1_000_000, "max-bytes": 1 << 30,
                  "max-delay-ms": 50}, id="aggressive"),
    # constant flushing: the threshold-boundary axis
    pytest.param({"max-rows": 64, "max-bytes": 2048, "max-delay-ms": 1},
                 id="tiny"),
]


@pytest.mark.parametrize("settings", SETTINGS)
@pytest.mark.parametrize("name", COALESCE_FAMILIES)
def test_goldens_exact_across_coalesce_settings(name, settings, tmp_path, _storage):
    from arroyo_tpu import config as cfg

    cfg.update({f"engine.coalesce.{k}": v for k, v in settings.items()})
    out = str(tmp_path / "out.json")
    eng = build(load_sql(name, out), 1, f"{name}-co")
    eng.run_to_completion(timeout=180)
    assert_outputs(name, out)


def test_checkpoint_restore_exact_with_aggressive_coalescing(tmp_path, _storage):
    """Barriers must align and snapshots stay byte-exact while rows are
    held in collectors mid-stream (the flush-on-broadcast rule e2e)."""
    from arroyo_tpu import config as cfg

    name = "tumbling_aggregates"
    cfg.update({"engine.coalesce.max-rows": 1_000_000,
                "engine.coalesce.max-bytes": 1 << 30,
                "engine.coalesce.max-delay-ms": 50,
                "testing.source-gate-epochs": 2})
    out = str(tmp_path / "out.json")
    sql = load_sql(name, out)
    try:
        eng = build(sql, 2, f"{name}-co-ckpt")
        eng.start()
        assert eng.checkpoint_and_wait(1, timeout=60)
        assert eng.checkpoint_and_wait(2, timeout=60, then_stop=True)
        eng.join(timeout=120)
    finally:
        cfg.update({"testing.source-gate-epochs": 0})
    eng2 = build(sql, 2, f"{name}-co-ckpt", restore_epoch=2)
    eng2.run_to_completion(timeout=180)
    assert_outputs(name, out)


@pytest.mark.chaos
def test_chaos_crash_mid_checkpoint_with_coalescing(tmp_path, _storage):
    """Chaos axis under aggressive coalescing: worker crash after epoch-2
    state lands but before completion; recovery from epoch 1 must still
    reproduce the goldens byte-exact with rows buffered in collectors."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu import faults
    from arroyo_tpu.state.tables import latest_complete_checkpoint

    name = "sliding_window"
    out = str(tmp_path / "out.json")
    sql = load_sql(name, out)
    job_id = f"{name}-co-chaos"
    cfg.update({"engine.coalesce.max-rows": 1_000_000,
                "engine.coalesce.max-delay-ms": 50,
                "testing.source-gate-epochs": 2})
    inj = faults.install("worker:crash@barrier=2&step=1", seed=1337)
    try:
        eng = build(sql, 2, job_id)
        eng.start()
        assert eng.checkpoint_and_wait(1, timeout=60)
        with pytest.raises(RuntimeError, match="injected"):
            if eng.checkpoint_and_wait(2, timeout=60):
                raise AssertionError("epoch 2 completed despite injected crash")
            eng.join(timeout=60)
    finally:
        faults.clear()
        cfg.update({"testing.source-gate-epochs": 0})
    assert inj.fired_log, "crash fault never fired"
    storage_url = cfg.config().get("checkpoint.storage-url")
    assert latest_complete_checkpoint(storage_url, job_id) == 1
    eng2 = build(sql, 2, job_id, restore_epoch=1)
    eng2.run_to_completion(timeout=180)
    assert_outputs(name, out)


# ------------------------------------------------ fused multi-window close


class FakeCollector:
    def __init__(self):
        self.batches = []

    def collect(self, batch):
        self.batches.append(batch)

    def broadcast(self, signal):
        pass


def _join_rows(col):
    rows = []
    for bt in col.batches:
        rows.extend(bt.to_pylist())
    return sorted(
        repr((r[TIMESTAMP_FIELD], r["lid"], r["lv"], r["rid"], r["rv"]))
        for r in rows
    )


def _feed_windows(op, ctx, col, rng):
    from test_joins import kb

    for t in (100, 200, 300, 400):
        nl, nr = int(rng.integers(3, 40)), int(rng.integers(3, 40))
        op.process_batch(
            kb([t] * nl, rng.integers(0, 9, nl).tolist(),
               [f"l{t}_{i}" for i in range(nl)]), ctx, col, input_index=0)
        op.process_batch(
            kb([t] * nr, rng.integers(0, 9, nr).tolist(),
               [f"r{t}_{i}" for i in range(nr)]), ctx, col, input_index=1)


@pytest.mark.parametrize("jt", ["inner", "left", "right", "full"])
def test_fused_multi_window_close_matches_per_window(jt, _storage):
    """One watermark closing N windows (fused path) must emit exactly the
    (window, key) groups that N per-window watermarks emit."""
    from test_joins import two_input_ctx

    from arroyo_tpu.operators.joins import InstantJoin

    def run(close_per_window: bool):
        op = InstantJoin({
            "join_type": jt,
            "left_names": [("lid", "id"), ("lv", "v")],
            "right_names": [("rid", "id"), ("rv", "v")],
            "backend": "numpy",
        })
        ctx, col = two_input_ctx(), FakeCollector()
        rng = np.random.default_rng(41)
        _feed_windows(op, ctx, col, rng)
        if close_per_window:
            for t in (101, 201, 301, 401):
                op.handle_watermark(Watermark.event_time(t), ctx, col)
        else:
            op.handle_watermark(Watermark.event_time(401), ctx, col)
        op.on_close(ctx, col)
        return _join_rows(col)

    assert run(True) == run(False), jt


def test_fused_close_on_stream_end(_storage):
    """on_close with several buffered windows takes the fused path and
    emits the same groups as watermark-driven closes."""
    from test_joins import two_input_ctx

    from arroyo_tpu.operators.joins import InstantJoin

    def run(with_watermarks: bool):
        op = InstantJoin({
            "join_type": "inner",
            "left_names": [("lid", "id"), ("lv", "v")],
            "right_names": [("rid", "id"), ("rv", "v")],
            "backend": "numpy",
        })
        ctx, col = two_input_ctx(), FakeCollector()
        rng = np.random.default_rng(42)
        _feed_windows(op, ctx, col, rng)
        if with_watermarks:
            for t in (101, 201, 301, 401):
                op.handle_watermark(Watermark.event_time(t), ctx, col)
        op.on_close(ctx, col)
        return _join_rows(col)

    assert run(False) == run(True)
    # the fused path really was taken: everything emitted in few batches
    op_rows = run(False)
    assert len(op_rows) > 0


# ------------------------------------------------ data plane frame coalescing


def test_network_frame_coalescing_preserves_order(_storage):
    """Many small data frames + a signal over the coalescing send buffer:
    one write carries them all, receiver sees identical frames in order."""
    from arroyo_tpu import config as cfg
    from arroyo_tpu.engine.network import NetworkManager, RemoteDest
    from arroyo_tpu.native import available

    if not available():
        pytest.skip("native library unavailable")
    cfg.update({"engine.coalesce.max-delay-ms": 20})
    rx, tx = NetworkManager(), NetworkManager()
    peers = {0: ("127.0.0.1", rx.port), 1: ("127.0.0.1", tx.port)}
    rx.set_peers(peers)
    tx.set_peers(peers)
    got = []
    done = threading.Event()

    class Inbox:
        def put(self, idx, item):
            got.append((idx, item))
            if isinstance(item, Signal):
                done.set()

    quad = (0, 0, 1, 0)
    rx.register_receiver(quad, Inbox(), 7)
    rx.start()
    tx.start()
    dest = RemoteDest(tx, 0, quad)
    for i in range(10):
        dest.put(0, b(3, base=i * 3))
    dest.put(0, Signal.watermark_of(Watermark.event_time(99)))
    assert done.wait(timeout=10), "signal never arrived"
    try:
        assert len(got) == 11
        assert all(idx == 7 for idx, _ in got)
        for i in range(10):
            item = got[i][1]
            assert isinstance(item, Batch) and item.num_rows == 3
            assert np.array_equal(item["x"], np.arange(i * 3, i * 3 + 3))
        assert isinstance(got[10][1], Signal)
    finally:
        tx.close()
        rx.close()
