"""Checkpoint compaction + cleanup (reference parquet.rs:159/:214)."""

import os

import numpy as np
import pytest

from arroyo_tpu.batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from arroyo_tpu.operators.base import TableSpec
from arroyo_tpu.state.tables import (
    TableManager,
    checkpoint_dir,
    cleanup_checkpoints,
    compact_job,
    compact_operator,
    write_job_checkpoint_metadata,
)
from arroyo_tpu.types import TaskInfo


def _mk_batch(keys, ts, vals):
    return Batch({
        KEY_FIELD: np.array(keys, dtype=np.uint64),
        TIMESTAMP_FIELD: np.array(ts, dtype=np.int64),
        "v": np.array(vals, dtype=np.int64),
    })


def _checkpoint_two_subtasks(store, epoch):
    specs = [TableSpec("t", "expiring_time_key"), TableSpec("g", "global_keyed")]
    for sub in range(2):
        ti = TaskInfo("job", "op1", "agg", sub, 2)
        tm = TableManager(ti, store)
        lo, hi = ti.key_range
        # each subtask owns keys in its hash range
        base = lo + 1
        tm.expiring_time_key("t").insert(
            _mk_batch([base, base + 1], [1000 * (sub + 1), 2000 * (sub + 1)], [sub * 10, sub * 10 + 1])
        )
        tm.global_keyed("g").insert(f"k{sub}", sub * 100)
        tm.checkpoint(epoch, None)
    write_job_checkpoint_metadata(store, "job", epoch)
    return specs


def _restore_all(store, epoch, parallelism, specs):
    rows = []
    gvals = {}
    for sub in range(parallelism):
        ti = TaskInfo("job", "op1", "agg", sub, parallelism)
        tm = TableManager(ti, store)
        tm.restore(epoch, specs)
        for b in tm.expiring_time_key("t").all_batches():
            for r in b.to_pylist():
                rows.append((r[KEY_FIELD], r[TIMESTAMP_FIELD], r["v"]))
        gvals.update(dict(tm.global_keyed("g").items()))
    return sorted(rows), gvals


def test_compact_then_restore_rescaled(_storage):
    store = _storage
    specs = _checkpoint_two_subtasks(store, 1)
    before_rows, before_g = _restore_all(store, 1, 3, specs)
    removed = compact_operator(store, "job", 1, "op1")
    assert removed >= 2  # per-subtask shards merged away
    opdir = os.path.join(checkpoint_dir(store, "job", 1), "operator-op1")
    files = [f for f in os.listdir(opdir) if not f.startswith("metadata")]
    assert any("compacted-g1" in f for f in files)
    after_rows, after_g = _restore_all(store, 1, 3, specs)
    assert after_rows == before_rows
    assert after_g == before_g
    # double compaction is a no-op (generation-1 files are skipped)
    assert compact_operator(store, "job", 1, "op1") == 0


def test_compact_job_and_cleanup(_storage):
    store = _storage
    specs = _checkpoint_two_subtasks(store, 1)
    _checkpoint_two_subtasks(store, 2)
    assert compact_job(store, "job", 2) > 0
    assert cleanup_checkpoints(store, "job", min_epoch=2) == 1
    assert not os.path.isdir(checkpoint_dir(store, "job", 1))
    rows, g = _restore_all(store, 2, 2, specs)
    assert len(rows) == 4 and len(g) == 2


def test_restore_epoch_state_wins_over_final_fallback(tmp_path, _storage):
    """A drained subtask's "final" snapshot may hold a STALE CLONE of a key
    a live subtask kept advancing (global tables replicate across shards on
    restore — e.g. the single_file reader's line offset). When the final-dir
    fallback fills in the drained subtask, the epoch's own (fresher) value
    must win the merge, or a restore replays the source from the stale
    offset while downstream state keeps its rows — duplicated output (the
    exact corruption the 2-worker chaos axis once hit)."""
    url = _storage
    # subtask 0 (the live reader) snapshotted offset 285 at epoch 8
    tm0 = TableManager(TaskInfo("job", "src", "source", 0, 2), url)
    tm0.global_keyed("s").insert(0, 285)
    tm0.checkpoint(8, None)
    # subtask 1 drained long ago; its final snapshot carries a stale clone
    # of subtask 0's offset under the SAME key
    tm1 = TableManager(TaskInfo("job", "src", "source", 1, 2), url)
    tm1.global_keyed("s").insert(0, 30)
    tm1.checkpoint("final", None)
    # restore at epoch 8: subtask 1 is absent there -> final fallback kicks
    # in, but must not clobber the epoch's offset
    tmr = TableManager(TaskInfo("job", "src", "source", 0, 2), url)
    tmr.restore(8, [TableSpec("s", "global_keyed")])
    assert tmr.global_keyed("s").get(0) == 285
