"""Native UDF compile service + dylib host (compiler.py): C++ UDFs built by
the g++-based CompileService, published through the storage layer, loaded
via the ctypes C-ABI host, and callable from SQL end-to-end — including
through the REST API and a process-scheduler worker subprocess.
Reference: arroyo-compiler-service/src/lib.rs:57 + arroyo-udf-host/src/lib.rs:168."""

import json
import os

import numpy as np
import pytest

CPP_HYPOT = r"""
#include <cstdint>
#include <cmath>
extern "C" void hypot3(int64_t n, const double* a, const double* b, double* out) {
  for (int64_t i = 0; i < n; i++) out[i] = std::sqrt(a[i]*a[i] + b[i]*b[i]) + 3.0;
}
"""

CPP_SCALE = r"""
#include <cstdint>
extern "C" void scale7(int64_t n, const int64_t* a, int64_t* out) {
  for (int64_t i = 0; i < n; i++) out[i] = a[i] * 7;
}
"""


def test_compile_and_call_native_udf(_storage):
    from arroyo_tpu.compiler import CompileService, load_native_udf
    from arroyo_tpu.udf import drop_udf, lookup_udf

    spec = CompileService().build_udf(
        "hypot3", CPP_HYPOT, ["float64", "float64"], "float64")
    assert os.path.exists(spec.artifact_url)
    load_native_udf(spec)
    try:
        u = lookup_udf("hypot3")
        assert u is not None and u.vectorized
        out = u.fn(np.array([3.0, 5.0]), np.array([4.0, 12.0]))
        assert np.allclose(out, [8.0, 16.0])
    finally:
        drop_udf("hypot3")


def test_compile_error_surfaces(_storage):
    from arroyo_tpu.compiler import CompileError, CompileService

    with pytest.raises(CompileError, match="g\\+\\+ failed"):
        CompileService().build_udf("bad", "this is not C++", ["int64"], "int64")


def test_artifact_roundtrip_through_fake_s3(_storage):
    from arroyo_tpu.compiler import CompileService, load_native_udf
    from arroyo_tpu.state import storage as st
    from arroyo_tpu.udf import drop_udf, lookup_udf
    from test_storage import FakeS3

    client = FakeS3()
    st.set_s3_client(client)
    try:
        spec = CompileService("s3://udfs/artifacts").build_udf(
            "scale7", CPP_SCALE, ["int64"], "int64")
        assert spec.artifact_url.startswith("s3://")
        load_native_udf(spec)  # fetched into the local cache and dlopened
        out = lookup_udf("scale7").fn(np.array([1, 2, 3], dtype=np.int64))
        assert list(out) == [7, 14, 21]
    finally:
        st.set_s3_client(None)
        drop_udf("scale7")


def test_native_udf_via_rest_and_worker_subprocess(tmp_path, _storage):
    """POST /api/v1/udfs with C++ source -> pipeline using the UDF runs on a
    process-scheduler worker (specs travel via --udfs-file)."""
    import urllib.request

    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import ProcessScheduler
    from arroyo_tpu import config as cfg
    from arroyo_tpu.udf import drop_udf

    os.environ["ARROYO_TPU__CHECKPOINT__STORAGE_URL"] = cfg.config().get(
        "checkpoint.storage-url")
    inp = tmp_path / "in.json"
    with open(inp, "w") as f:
        for i in range(50):
            f.write(json.dumps({"x": i, "timestamp": i * 1000}) + "\n")
    out_path = tmp_path / "out.json"
    sql = f"""
CREATE TABLE src (timestamp TIMESTAMP, x BIGINT)
WITH (connector = 'single_file', path = '{inp}', format = 'json', type = 'source', event_time_field = 'timestamp');
CREATE TABLE snk (x BIGINT, y BIGINT)
WITH (connector = 'single_file', path = '{out_path}', format = 'json', type = 'sink');
INSERT INTO snk SELECT x, scale7(x) AS y FROM src;
"""
    db = Database()
    api = ApiServer(db).start()
    ctl = ControllerServer(db, ProcessScheduler()).start()
    try:
        def post(path, body):
            req = urllib.request.Request(
                f"http://127.0.0.1:{api.port}{path}",
                data=json.dumps(body).encode(), method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read())

        r = post("/api/v1/udfs", {
            "name": "scale7", "language": "cpp", "source": CPP_SCALE,
            "arg_dtypes": ["int64"], "return_dtype": "int64"})
        assert r["artifact_url"]
        r = post("/api/v1/pipelines", {"name": "udfpipe", "query": sql})
        jid = r["job_id"]
        state = ctl.wait_for_state(jid, "Finished", timeout=120)
        assert state == "Finished"
        rows = [json.loads(l) for l in open(out_path)]
        assert len(rows) == 50
        assert all(r["y"] == r["x"] * 7 for r in rows)
    finally:
        os.environ.pop("ARROYO_TPU__CHECKPOINT__STORAGE_URL", None)
        ctl.stop()
        api.stop()
        drop_udf("scale7")


def test_standalone_compile_service_http(_storage):
    """The compile service runs as its own daemon (reference
    arroyo-compiler-service deployable): POST /compile builds and publishes
    the dylib; the API delegates when compiler.endpoint is configured; a
    worker-side load of the returned artifact works."""
    import urllib.error
    import urllib.request

    from arroyo_tpu import config as cfg
    from arroyo_tpu.compiler import (CompileError, CompileServer,
                                     NativeUdfSpec, compile_udf,
                                     load_native_udf)
    from arroyo_tpu.udf import drop_udf, lookup_udf

    srv = CompileServer().start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with urllib.request.urlopen(f"{base}/status") as r:
            assert json.loads(r.read())["ok"]
        req = urllib.request.Request(
            f"{base}/compile",
            data=json.dumps({"name": "scale7", "source": CPP_SCALE,
                             "arg_dtypes": ["int64"],
                             "return_dtype": "int64"}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            out = json.loads(r.read())
        assert out["artifact_url"].endswith(".so")
        load_native_udf(NativeUdfSpec(
            out["name"], tuple(out["arg_dtypes"]), out["return_dtype"],
            out["artifact_url"]))
        u = lookup_udf("scale7")
        assert u is not None
        assert list(u.fn(np.arange(4, dtype=np.int64))) == [0, 7, 14, 21]
        drop_udf("scale7")

        # bad source -> 400 with the compiler diagnostic
        req = urllib.request.Request(
            f"{base}/compile",
            data=json.dumps({"name": "bad", "source": "not C++",
                             "arg_dtypes": [], "return_dtype": "int64"}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "g++" in json.loads(e.read())["error"]

        # the API-side builder delegates through compiler.endpoint
        cfg.update({"compiler.endpoint": base})
        try:
            spec = compile_udf("scale7", CPP_SCALE, ["int64"], "int64")
            assert spec.artifact_url == out["artifact_url"]  # content-addressed
            with pytest.raises(CompileError, match="g\\+\\+"):
                compile_udf("bad", "not C++", [], "int64")
        finally:
            cfg.update({"compiler.endpoint": None})
    finally:
        srv.stop()
