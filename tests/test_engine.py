"""End-to-end engine tests with hand-built graphs (reference test strategy
SURVEY §4.3: watermark merge, hash shuffle, queue backpressure)."""

import numpy as np

from arroyo_tpu.batch import Schema, Field, TIMESTAMP_FIELD
from arroyo_tpu.engine import Engine, run_graph
from arroyo_tpu.expr import BinOp, Col, Lit
from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

DUMMY = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])


def impulse_to_vec(count=100, parallelism=1, filter_expr=None, value_cfg=None):
    g = Graph()
    rows: list = []
    g.add_node(Node("src", OpName.SOURCE,
                    {"connector": "impulse", "message_count": count}, parallelism))
    cfg = value_cfg or {"filter": filter_expr}
    g.add_node(Node("map", OpName.VALUE, cfg, parallelism))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
    g.add_edge("src", "map", EdgeType.FORWARD, DUMMY)
    g.add_edge("map", "sink", EdgeType.SHUFFLE, DUMMY)
    return g, rows


def test_linear_pipeline_completes():
    g, rows = impulse_to_vec(count=100)
    run_graph(g, job_id="t1", timeout=30)
    assert len(rows) == 100
    counters = sorted(r["counter"] for r in rows)
    assert counters == list(range(100))


def test_filter():
    f = BinOp("==", BinOp("%", Col("counter"), Lit(2)), Lit(0))
    g, rows = impulse_to_vec(count=100, filter_expr=f)
    run_graph(g, job_id="t2", timeout=30)
    assert sorted(r["counter"] for r in rows) == list(range(0, 100, 2))


def test_projection():
    cfg = {"projections": [("doubled", BinOp("*", Col("counter"), Lit(2)))]}
    g, rows = impulse_to_vec(count=10, value_cfg=cfg)
    run_graph(g, job_id="t3", timeout=30)
    assert sorted(r["doubled"] for r in rows) == list(range(0, 20, 2))


def test_parallel_sources_and_shuffle():
    g, rows = impulse_to_vec(count=50, parallelism=3)
    run_graph(g, job_id="t4", timeout=30)
    # 3 subtasks x 50 messages each
    assert len(rows) == 150
    by_sub = {}
    for r in rows:
        by_sub.setdefault(r["subtask_index"], []).append(r["counter"])
    assert set(by_sub) == {0, 1, 2}
    for counters in by_sub.values():
        assert sorted(counters) == list(range(50))


def test_keyed_shuffle_partitions_by_key():
    g = Graph()
    rows: list = []
    g.add_node(Node("src", OpName.SOURCE, {"connector": "impulse", "message_count": 200}, 1))
    g.add_node(Node("key", OpName.KEY, {"keys": [("k", BinOp("%", Col("counter"), Lit(10)))]}, 1))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows, "include_internal": True}, 4))
    g.add_edge("src", "key", EdgeType.FORWARD, DUMMY)
    g.add_edge("key", "sink", EdgeType.SHUFFLE, DUMMY)
    run_graph(g, job_id="t5", timeout=30)
    assert len(rows) == 200
    # all rows with the same key hash must have landed in one partition:
    # verify hash determinism instead (vec sink loses partition identity),
    # and that every key appears exactly 20 times
    from collections import Counter

    c = Counter(r["k"] for r in rows)
    assert all(v == 20 for v in c.values()) and len(c) == 10


def test_checkpoint_and_restore(tmp_path):
    """Run, checkpoint mid-stream, simulate failure, restore from epoch."""
    import json, os
    from arroyo_tpu.config import config

    storage = config().get("checkpoint.storage-url")
    path = tmp_path / "out.jsonl"

    def build(rows):
        g = Graph()
        g.add_node(Node("src", OpName.SOURCE,
                        {"connector": "impulse", "message_count": 5000, "event_rate": 5000}, 1))
        g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
        g.add_edge("src", "sink", EdgeType.FORWARD, DUMMY)
        return g

    rows1: list = []
    eng = Engine(build(rows1), job_id="ckpt")
    eng.start()
    assert eng.checkpoint_and_wait(1, timeout=30)
    # stop without finishing (simulated failure: discard engine)
    eng.stop()
    eng.join(timeout=30)
    n_before = len(rows1)
    assert 0 < n_before < 5000

    from arroyo_tpu.state.tables import latest_complete_checkpoint

    assert latest_complete_checkpoint(storage, "ckpt") == 1

    rows2: list = []
    eng2 = Engine(build(rows2), job_id="ckpt", restore_epoch=1)
    eng2.run_to_completion(timeout=60)
    counters2 = sorted(r["counter"] for r in rows2)
    # restart resumed from the checkpointed offset, not zero
    assert counters2[0] > 0
    assert counters2[-1] == 4999
    # exactly-once relative to the checkpoint: no gaps, no duplicates
    assert counters2 == list(range(counters2[0], 5000))


def test_task_failure_aborts_pipeline_promptly():
    """A failing operator must tear the pipeline down (sources stopped,
    inboxes closed) and surface the error from join()."""
    import time
    from arroyo_tpu.engine.engine import register_operator
    from arroyo_tpu.graph import OpName
    from arroyo_tpu.operators.base import Operator

    class Exploder(Operator):
        def process_batch(self, batch, ctx, collector, input_index=0):
            raise RuntimeError("boom in operator")

    from arroyo_tpu.engine import engine as engine_mod

    saved = engine_mod._CONSTRUCTORS.get(OpName.ASYNC_UDF)
    register_operator(OpName.ASYNC_UDF)(lambda cfg: Exploder())
    try:
        g = Graph()
        g.add_node(Node("src", OpName.SOURCE,
                        {"connector": "impulse", "message_count": None, "event_rate": 50000}, 1))
        g.add_node(Node("bad", OpName.ASYNC_UDF, {}, 1))
        g.add_edge("src", "bad", EdgeType.FORWARD, DUMMY)
        eng = Engine(g, job_id="fail")
        eng.start()
        t0 = time.monotonic()
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="boom in operator"):
            eng.join(timeout=30)
        assert time.monotonic() - t0 < 15  # aborted promptly, not via timeout
    finally:
        # restore the real async-udf constructor (the registry is global)
        if saved is not None:
            engine_mod._CONSTRUCTORS[OpName.ASYNC_UDF] = saved


def test_backpressure_bounded_queue():
    from arroyo_tpu.engine.queues import TaskInbox
    from arroyo_tpu.batch import Batch
    import threading, time

    inbox = TaskInbox(1, row_budget=100)
    b = Batch({"x": np.arange(60)})
    inbox.put(0, b)
    blocked_done = []

    def blocked_put():
        inbox.put(0, Batch({"x": np.arange(60)}))  # 60+60 > 100 -> blocks
        blocked_done.append(True)

    t = threading.Thread(target=blocked_put, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not blocked_done
    idx, item = inbox.get()
    inbox.release(idx, item)
    t.join(timeout=5)
    assert blocked_done


def test_assignment_unknown_node_rejected():
    """Assignments computed against a differently-chained graph must be
    rejected, not silently defaulted to worker 0 (advisor r2 low)."""
    import pytest

    from arroyo_tpu.batch import Schema, TIMESTAMP_FIELD
    from arroyo_tpu.engine import Engine
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "impulse", "message_count": 1,
        "interval_micros": 1000, "start_time_micros": 0}, 1))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": []}, 1))
    g.add_edge("src", "sink", EdgeType.FORWARD, S)
    with pytest.raises(ValueError, match="assignment references node ids"):
        Engine(g, assignment={("src+sink", 0): 0}, worker_index=0)


def test_restore_graph_mismatch_rejected(tmp_path):
    """Restoring a checkpoint whose operator ids don't exist in the current
    graph (e.g. chaining flipped across a restore) must fail loudly instead
    of silently dropping state (advisor r2 low)."""
    import pytest

    from arroyo_tpu.batch import Schema, TIMESTAMP_FIELD
    from arroyo_tpu.engine import Engine
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName
    from arroyo_tpu.state.tables import write_job_checkpoint_metadata

    storage = str(tmp_path / "ck")
    write_job_checkpoint_metadata(storage, "j1", 1, {"operators": ["wm+key+agg"]})
    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "impulse", "message_count": 1,
        "interval_micros": 1000, "start_time_micros": 0}, 1))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": []}, 1))
    g.add_edge("src", "sink", EdgeType.FORWARD, S)
    eng = Engine(g, job_id="j1", storage_url=storage, restore_epoch=1)
    with pytest.raises(RuntimeError, match="chaining"):
        eng.build()


def test_graph_ir_round_trip_runs_identically(tmp_path, _storage):
    """A planner-produced graph serializes to JSON (expressions as tagged
    ASTs, schemas as tagged dicts) and the reloaded graph runs to the same
    output — the shipped-IR contract (reference: protobuf ArrowProgram in
    StartExecutionReq, workers never re-plan)."""
    import json as _json

    from arroyo_tpu.graph import Graph
    from arroyo_tpu.sql import plan_query

    inp = tmp_path / "in.json"
    with open(inp, "w") as f:
        for i in range(120):
            f.write(_json.dumps({"k": i % 4, "v": i, "timestamp": i * 100_000}) + "\n")
    out1, out2 = str(tmp_path / "o1.json"), str(tmp_path / "o2.json")

    def sql(out):
        return f"""
CREATE TABLE src (timestamp TIMESTAMP, k BIGINT, v BIGINT)
WITH (connector = 'single_file', path = '{inp}', format = 'json', type = 'source', event_time_field = 'timestamp');
CREATE TABLE snk (k BIGINT, total BIGINT, n BIGINT, label TEXT)
WITH (connector = 'single_file', path = '{out}', format = 'json', type = 'sink');
INSERT INTO snk
SELECT k, total, n, CASE WHEN total > 100 THEN 'big' ELSE 'small' END AS label
FROM (
  SELECT k, sum(v * 2) AS total, count(*) AS n,
    tumble(interval '4 seconds') AS w
  FROM src GROUP BY k, w
) t;
"""

    pp = plan_query(sql(out1))
    dumped = pp.graph.dumps()  # through actual JSON text
    reloaded = Graph.loads(dumped)
    Engine(pp.graph, job_id="ir-live").run_to_completion(timeout=60)
    # rewrite the sink path on the reloaded graph so outputs don't collide
    for n in reloaded.nodes.values():
        if n.config.get("path") == out1:
            n.config["path"] = out2
    Engine(reloaded, job_id="ir-shipped").run_to_completion(timeout=60)
    rows1 = sorted(_json.loads(l)["total"] for l in open(out1) if l.strip())
    rows2 = sorted(_json.loads(l)["total"] for l in open(out2) if l.strip())
    assert rows1 == rows2 and len(rows1) > 0
    lab1 = sorted((_json.loads(l)["k"], _json.loads(l)["label"]) for l in open(out1))
    lab2 = sorted((_json.loads(l)["k"], _json.loads(l)["label"]) for l in open(out2))
    assert lab1 == lab2


def test_checkpoint_and_wait_distinct_outcomes(tmp_path, _storage):
    """checkpoint_and_wait must tell its three exits apart: a drained
    pipeline ("finished") is a stop, a stuck barrier ("timeout") is a
    failure whose diagnostic names the subtasks that never acked, and only
    "completed" is truthy."""
    import time

    from arroyo_tpu.engine import engine as engine_mod
    from arroyo_tpu.engine.engine import CheckpointWait, register_operator
    from arroyo_tpu.operators.base import Operator

    # (a) pipeline finished before the barrier -> "finished", falsy
    g, _rows = impulse_to_vec(count=10)
    eng = Engine(g, job_id="cw-finished")
    eng.start()
    eng.join(timeout=30)
    res = eng.checkpoint_and_wait(1, timeout=5)
    assert isinstance(res, CheckpointWait)
    assert not res and res.outcome == "finished" and res.missing == ()

    # (b) a wedged operator -> "timeout", with the unacked subtask named
    class Staller(Operator):
        def process_batch(self, batch, ctx, collector, input_index=0):
            time.sleep(5)

    saved = engine_mod._CONSTRUCTORS.get(OpName.ASYNC_UDF)
    register_operator(OpName.ASYNC_UDF)(lambda cfg: Staller())
    try:
        g2 = Graph()
        g2.add_node(Node("src", OpName.SOURCE,
                         {"connector": "impulse", "message_count": None,
                          "event_rate": 5000}, 1))
        g2.add_node(Node("stall", OpName.ASYNC_UDF, {}, 1))
        g2.add_edge("src", "stall", EdgeType.FORWARD, DUMMY)
        eng2 = Engine(g2, job_id="cw-timeout")
        eng2.start()
        time.sleep(0.3)  # let the staller pick up a batch
        res2 = eng2.checkpoint_and_wait(1, timeout=1.5)
        assert not res2 and res2.outcome == "timeout"
        assert ("stall", 0) in res2.missing, res2
        assert "stall" in repr(res2)
        eng2._abort()
    finally:
        if saved is not None:
            engine_mod._CONSTRUCTORS[OpName.ASYNC_UDF] = saved
