#!/usr/bin/env python
"""Round benchmark: the BASELINE.md Nexmark matrix on the TPU backend.

Configs (BASELINE.md "Benchmark configs"):
  q7 — bid stream -> tumbling 10s MAX(price)+COUNT per auction  (primary)
  q5 — bid stream -> sliding 10s/2s COUNT per auction (hot items core)
  q8 — auctions JOIN bids on auction id per tumbling 10s window
       (device-lowered InstantJoin)

Every config runs the full framework (vectorized generator, host engine,
device steps) on the default platform (the real TPU chip under the driver),
asserts EXACT per-window parity against an independent vectorized-numpy
oracle computed from the deterministic generator, and measures p50/p99
watermark-to-emit latency (wall clock from watermark injection at the
watermark operator to row arrival at the sink).

The numpy-backend run of q7 is the CPU baseline proxy for vs_baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
"""

from __future__ import annotations

import gc
import json
import os
import statistics
import sys
import time

import numpy as np

WIDTH = 10_000_000  # 10 s tumbling / sliding width
SLIDE = 2_000_000   # q5 slide


# ---------------------------------------------------------------- graphs


def _source_node(event_count, columns, inter_event=1000):
    from arroyo_tpu.graph import Node, OpName

    return Node("src", OpName.SOURCE, {
        "connector": "nexmark", "event_count": event_count,
        "inter_event_micros": inter_event, "first_event_micros": 0,
        "include_strings": False, "columns": columns}, 1)


def build_q7(rows_sink, backend, event_count, latency_log, arrival_walls):
    from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
    from arroyo_tpu.expr import Col
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(_source_node(event_count, ["bid.auction", "bid.price"]))
    g.add_node(Node("bids", OpName.VALUE, {
        "projections": [("auction", Col("bid.auction")), ("price", Col("bid.price"))],
        "filter": Col("bid")}, 1))
    g.add_node(Node("wm", OpName.WATERMARK, {
        "expr": Col(TIMESTAMP_FIELD), "interval_micros": 1_000_000,
        "latency_log": latency_log}, 1))
    g.add_node(Node("key", OpName.KEY, {"keys": [("auction", Col("auction"))]}, 1))
    g.add_node(Node("agg", OpName.TUMBLING_AGGREGATE, {
        "width_micros": WIDTH,
        "key_fields": ["auction"],
        "aggregates": [("max_price", "max", Col("price")), ("bids", "count", None)],
        "input_dtype_of": lambda e: np.dtype(np.int64),
        "backend": backend}, 1))
    g.add_node(Node("sink", OpName.SINK, {
        "connector": "vec", "rows": rows_sink, "columnar": True,
        "arrival_walls": arrival_walls}, 1))
    g.add_edge("src", "bids", EdgeType.FORWARD, S)
    g.add_edge("bids", "wm", EdgeType.FORWARD, S)
    g.add_edge("wm", "key", EdgeType.FORWARD, S)
    g.add_edge("key", "agg", EdgeType.SHUFFLE, S)
    g.add_edge("agg", "sink", EdgeType.FORWARD, S)
    return g


def build_q5(rows_sink, backend, event_count, latency_log, arrival_walls):
    from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
    from arroyo_tpu.expr import Col
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(_source_node(event_count, ["bid.auction"]))
    g.add_node(Node("bids", OpName.VALUE, {
        "projections": [("auction", Col("bid.auction"))],
        "filter": Col("bid")}, 1))
    g.add_node(Node("wm", OpName.WATERMARK, {
        "expr": Col(TIMESTAMP_FIELD), "interval_micros": 1_000_000,
        "latency_log": latency_log}, 1))
    g.add_node(Node("key", OpName.KEY, {"keys": [("auction", Col("auction"))]}, 1))
    g.add_node(Node("agg", OpName.SLIDING_AGGREGATE, {
        "width_micros": WIDTH, "slide_micros": SLIDE,
        "key_fields": ["auction"],
        "aggregates": [("bids", "count", None)],
        "input_dtype_of": lambda e: np.dtype(np.int64),
        "backend": backend}, 1))
    g.add_node(Node("sink", OpName.SINK, {
        "connector": "vec", "rows": rows_sink, "columnar": True,
        "arrival_walls": arrival_walls}, 1))
    g.add_edge("src", "bids", EdgeType.FORWARD, S)
    g.add_edge("bids", "wm", EdgeType.FORWARD, S)
    g.add_edge("wm", "key", EdgeType.FORWARD, S)
    g.add_edge("key", "agg", EdgeType.SHUFFLE, S)
    g.add_edge("agg", "sink", EdgeType.FORWARD, S)
    return g


SESSION_GAP = 2_000_000  # qs session gap


def build_qs(rows_sink, backend, event_count, latency_log, arrival_walls):
    """Session windows per bidder (BASELINE config #5 shape): bursty
    per-bidder activity with gaps — COUNT + SUM(price) per session."""
    from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
    from arroyo_tpu.expr import Col
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(_source_node(event_count, ["bid.bidder", "bid.price"]))
    g.add_node(Node("bids", OpName.VALUE, {
        "projections": [("bidder", Col("bid.bidder")), ("price", Col("bid.price"))],
        "filter": Col("bid")}, 1))
    g.add_node(Node("wm", OpName.WATERMARK, {
        "expr": Col(TIMESTAMP_FIELD), "interval_micros": 1_000_000,
        "latency_log": latency_log}, 1))
    g.add_node(Node("key", OpName.KEY, {"keys": [("bidder", Col("bidder"))]}, 1))
    g.add_node(Node("agg", OpName.SESSION_AGGREGATE, {
        "gap_micros": SESSION_GAP,
        "key_fields": ["bidder"],
        "aggregates": [("bids", "count", None), ("spend", "sum", Col("price"))],
        "input_dtype_of": lambda e: np.dtype(np.int64)}, 1))
    g.add_node(Node("sink", OpName.SINK, {
        "connector": "vec", "rows": rows_sink, "columnar": True,
        "arrival_walls": arrival_walls}, 1))
    g.add_edge("src", "bids", EdgeType.FORWARD, S)
    g.add_edge("bids", "wm", EdgeType.FORWARD, S)
    g.add_edge("wm", "key", EdgeType.FORWARD, S)
    g.add_edge("key", "agg", EdgeType.SHUFFLE, S)
    g.add_edge("agg", "sink", EdgeType.FORWARD, S)
    return g


def build_q8(rows_sink, backend, event_count, latency_log, arrival_walls):
    """Auctions JOIN bids on auction id within tumbling windows. Denser
    event time (100us) so windows carry join-sized inputs."""
    from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
    from arroyo_tpu.expr import BinOp, Col, Lit
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    win = BinOp("*", BinOp("/", Col(TIMESTAMP_FIELD), Lit(WIDTH)), Lit(WIDTH))
    g = Graph()
    g.add_node(_source_node(event_count, ["auction.id", "bid.auction"],
                            inter_event=100))
    # watermark floored to the window start: join rows are re-stamped with
    # their window start, so a raw event-time watermark would close the
    # current window mid-stream and drop its remaining rows as late
    g.add_node(Node("wm", OpName.WATERMARK, {
        "expr": win, "latency_log": latency_log}, 1))
    # stamp rows with their window start; InstantJoin buckets by timestamp
    g.add_node(Node("auctions", OpName.VALUE, {
        "projections": [("id", Col("auction.id")), (TIMESTAMP_FIELD, win)],
        "filter": Col("auction")}, 1))
    g.add_node(Node("akey", OpName.KEY, {"keys": [("id", Col("id"))]}, 1))
    g.add_node(Node("bids", OpName.VALUE, {
        "projections": [("auction", Col("bid.auction")), (TIMESTAMP_FIELD, win)],
        "filter": Col("bid")}, 1))
    g.add_node(Node("bkey", OpName.KEY, {"keys": [("auction", Col("auction"))]}, 1))
    g.add_node(Node("join", OpName.INSTANT_JOIN, {
        "join_type": "inner",
        "left_names": [("id", "id")],
        "right_names": [("bid_auction", "auction")],
        "backend": backend}, 1))
    g.add_node(Node("sink", OpName.SINK, {
        "connector": "vec", "rows": rows_sink, "columnar": True,
        "include_internal": True,  # the join's window rides _timestamp
        "arrival_walls": arrival_walls}, 1))
    g.add_edge("src", "wm", EdgeType.FORWARD, S)
    g.add_edge("wm", "auctions", EdgeType.FORWARD, S)
    g.add_edge("wm", "bids", EdgeType.FORWARD, S)
    g.add_edge("auctions", "akey", EdgeType.FORWARD, S)
    g.add_edge("bids", "bkey", EdgeType.FORWARD, S)
    g.add_edge("akey", "join", EdgeType.LEFT_JOIN, S)
    g.add_edge("bkey", "join", EdgeType.RIGHT_JOIN, S)
    g.add_edge("join", "sink", EdgeType.FORWARD, S)
    return g


# ---------------------------------------------------------------- oracles


def _gen_events(event_count, columns, inter_event=1000):
    """Exact replay of the deterministic generator (no engine)."""
    from arroyo_tpu.connectors.nexmark import NexmarkSource

    src = NexmarkSource({
        "event_count": event_count, "inter_event_micros": inter_event,
        "first_event_micros": 0, "include_strings": False,
        "columns": columns})
    return src._generate(np.arange(event_count, dtype=np.int64))


def oracle_q7(event_count):
    """(window_start, auction) -> (max_price, count), vectorized."""
    from arroyo_tpu.batch import TIMESTAMP_FIELD

    b = _gen_events(event_count, ["bid.auction", "bid.price"])
    is_bid = np.asarray(b["bid"])
    auc = np.asarray(b["bid.auction"])[is_bid]
    price = np.asarray(b["bid.price"])[is_bid]
    ts = np.asarray(b[TIMESTAMP_FIELD])[is_bid]
    w = (ts // WIDTH) * WIDTH
    group = np.stack([w, auc], axis=1)
    uniq, inv = np.unique(group, axis=0, return_inverse=True)
    mx = np.full(len(uniq), np.iinfo(np.int64).min, dtype=np.int64)
    np.maximum.at(mx, inv, price)
    cnt = np.bincount(inv, minlength=len(uniq))
    return {(int(uniq[i, 0]), int(uniq[i, 1])): (int(mx[i]), int(cnt[i]))
            for i in range(len(uniq))}


def oracle_q5(event_count):
    """(window_start, auction) -> count over sliding 10s/2s windows."""
    from arroyo_tpu.batch import TIMESTAMP_FIELD

    b = _gen_events(event_count, ["bid.auction"])
    is_bid = np.asarray(b["bid"])
    auc = np.asarray(b["bid.auction"])[is_bid]
    ts = np.asarray(b[TIMESTAMP_FIELD])[is_bid]
    sbin = (ts // SLIDE) * SLIDE
    group = np.stack([sbin, auc], axis=1)
    uniq, inv = np.unique(group, axis=0, return_inverse=True)
    cnt = np.bincount(inv, minlength=len(uniq))
    out: dict = {}
    n_bins = WIDTH // SLIDE
    for i in range(len(uniq)):
        sb, a, c = int(uniq[i, 0]), int(uniq[i, 1]), int(cnt[i])
        # slide-bin sb contributes to windows starting sb-(W-S) .. sb
        for k in range(n_bins):
            start = sb - k * SLIDE
            key = (start, a)
            out[key] = out.get(key, 0) + c
    return out


def oracle_qs(event_count):
    """(session_start, bidder) -> (count, spend) with gap-merged sessions."""
    from arroyo_tpu.batch import TIMESTAMP_FIELD

    b = _gen_events(event_count, ["bid.bidder", "bid.price"])
    is_bid = np.asarray(b["bid"])
    bidder = np.asarray(b["bid.bidder"])[is_bid]
    price = np.asarray(b["bid.price"])[is_bid]
    ts = np.asarray(b[TIMESTAMP_FIELD])[is_bid]
    out: dict = {}
    order = np.lexsort((ts, bidder))
    bs, tss, ps = bidder[order], ts[order], price[order]
    i0 = 0
    for i in range(1, len(bs) + 1):
        if i == len(bs) or bs[i] != bs[i - 1] or tss[i] - tss[i - 1] > SESSION_GAP:
            out[(int(tss[i0]), int(bs[i0]))] = (i - i0, int(ps[i0:i].sum()))
            i0 = i
    return out


def oracle_q8(event_count):
    """(window_start, auction_id) -> n_auction_events * n_bid_events."""
    from arroyo_tpu.batch import TIMESTAMP_FIELD

    b = _gen_events(event_count, ["auction.id", "bid.auction"], inter_event=100)
    ts = np.asarray(b[TIMESTAMP_FIELD])
    w = (ts // WIDTH) * WIDTH
    is_a = np.asarray(b["auction"])
    is_b = np.asarray(b["bid"])

    def counts(mask, ids):
        grp = np.stack([w[mask], ids[mask]], axis=1)
        uniq, inv = np.unique(grp, axis=0, return_inverse=True)
        c = np.bincount(inv, minlength=len(uniq))
        return {(int(uniq[i, 0]), int(uniq[i, 1])): int(c[i]) for i in range(len(uniq))}

    na = counts(is_a, np.asarray(b["auction.id"]))
    nb = counts(is_b, np.asarray(b["bid.auction"]))
    return {k: na[k] * nb[k] for k in na.keys() & nb.keys()}


# ---------------------------------------------------------------- running


def run_config(name, build, backend, event_count, batch_size, queue_mult=2):
    from arroyo_tpu import config as cfg
    from arroyo_tpu.engine import run_graph
    from arroyo_tpu.metrics import registry

    # fresh histograms per run: the coalesce breakdown reports THIS rep
    registry.clear_job(f"bench-{name}-{backend}")
    # queue depth sweep (r5, CPU): 2x batch beats 4x on every config
    # (less cache-cold buffering); q8 runs 1x — watermark-to-emit latency
    # is queue-transit bound and the join tolerates the shallower pipeline
    cfg.update({
        "pipeline.source-batch-size": batch_size,
        "device.batch-capacity": batch_size,
        "worker.queue-size": queue_mult * batch_size if backend == "jax" else batch_size,
    })
    rows: list = []
    latency_log: list = []
    arrival_walls: list = []
    g = build(rows, backend, event_count, latency_log, arrival_walls)
    t0 = time.perf_counter()
    run_graph(g, job_id=f"bench-{name}-{backend}", timeout=1800)
    wall = time.perf_counter() - t0
    return wall, rows, latency_log, arrival_walls


def coalesce_breakdown(job_id):
    """Aggregate the instrumentation histograms (emit-batch rows,
    queue-transit seconds, sink end-to-end latency) across every task of
    one job (last rep: run_config clears)."""
    from arroyo_tpu.metrics import (EMIT_ROWS_BUCKETS, SINK_LATENCY_BUCKETS,
                                    TRANSIT_BUCKETS, Histogram, registry)

    em, qt, sk = (Histogram(EMIT_ROWS_BUCKETS), Histogram(TRANSIT_BUCKETS),
                  Histogram(SINK_LATENCY_BUCKETS))
    for t in registry.snapshot():
        if t.job_id != job_id:
            continue
        for agg, h in ((em, t.emit_batch_rows), (qt, t.queue_transit),
                       (sk, t.sink_event_latency)):
            agg.counts = [a + b for a, b in zip(agg.counts, h.counts)]
            agg.count += h.count
            agg.sum += h.sum
    return em, qt, sk


def histogram_summary(h, scale=1.0):
    """Compact JSON-able distribution summary; overflow-bucket quantiles
    are clamped lower bounds flagged with '>' (Histogram.quantile_str)."""
    return {
        "count": h.count,
        "mean": round(h.mean() * scale, 3),
        "p50": h.quantile_str(0.5, scale=scale),
        "p90": h.quantile_str(0.9, scale=scale),
        "p99": h.quantile_str(0.99, scale=scale),
    }


def latency_percentiles(rows, latency_log, arrival_walls, window_end_of):
    """Per-row wall latency from closing-watermark injection to sink
    arrival; rows flushed at end-of-stream (no covering watermark) are
    excluded. Returns (p50_ms, p99_ms, n)."""
    if not latency_log:
        return None, None, 0
    wm_vals = np.array([v for v, _ in latency_log], dtype=np.int64)
    wm_wall = np.array([wl for _, wl in latency_log])
    lats: list[np.ndarray] = []
    for batch, wall in zip(rows, arrival_walls):
        ends = window_end_of(batch)
        idx = np.searchsorted(wm_vals, ends, side="left")
        ok = idx < len(wm_vals)
        if ok.any():
            lats.append(wall - wm_wall[idx[ok]])
    if not lats:
        return None, None, 0
    all_l = np.concatenate(lats) * 1000.0
    return float(np.percentile(all_l, 50)), float(np.percentile(all_l, 99)), len(all_l)


def check_parity_q7(rows, event_count):
    got: dict = {}
    for b in rows:
        ws = np.asarray(b["window_start"])
        auc = np.asarray(b["auction"])
        mx = np.asarray(b["max_price"])
        cnt = np.asarray(b["bids"])
        for i in range(b.num_rows):
            got[(int(ws[i]), int(auc[i]))] = (int(mx[i]), int(cnt[i]))
    want = oracle_q7(event_count)
    assert got == want, (
        f"q7 parity failure: {len(got)} windows vs {len(want)}; "
        f"first diff: {next(iter(set(got.items()) ^ set(want.items())), None)}"
    )
    return sum(c for _m, c in got.values())


def check_parity_q5(rows, event_count):
    got: dict = {}
    for b in rows:
        ws = np.asarray(b["window_start"])
        auc = np.asarray(b["auction"])
        cnt = np.asarray(b["bids"])
        for i in range(b.num_rows):
            got[(int(ws[i]), int(auc[i]))] = got.get((int(ws[i]), int(auc[i])), 0) + int(cnt[i])
    want = oracle_q5(event_count)
    assert got == want, (
        f"q5 parity failure: {len(got)} (window,auction) rows vs {len(want)}; "
        f"first diff: {next(iter(set(got.items()) ^ set(want.items())), None)}"
    )
    return sum(got.values())


def check_parity_qs(rows, event_count):
    got: dict = {}
    for b in rows:
        ws = np.asarray(b["window_start"])
        bd = np.asarray(b["bidder"])
        cnt = np.asarray(b["bids"])
        sp = np.asarray(b["spend"])
        for i in range(b.num_rows):
            got[(int(ws[i]), int(bd[i]))] = (int(cnt[i]), int(sp[i]))
    want = oracle_qs(event_count)
    assert got == want, (
        f"qs parity failure: {len(got)} sessions vs {len(want)}; "
        f"first diff: {next(iter(set(got.items()) ^ set(want.items())), None)}"
    )
    return sum(c for c, _s in got.values())


def check_parity_q8(rows, event_count):
    from arroyo_tpu.batch import TIMESTAMP_FIELD

    got: dict = {}
    for b in rows:
        w = np.asarray(b[TIMESTAMP_FIELD])
        ids = np.asarray(b["id"])
        for i in range(b.num_rows):
            k = (int(w[i]), int(ids[i]))
            got[k] = got.get(k, 0) + 1
    want = oracle_q8(event_count)
    assert got == want, (
        f"q8 parity failure: {len(got)} (window,id) groups vs {len(want)}; "
        f"first diff: {next(iter(set(got.items()) ^ set(want.items())), None)}"
    )
    return sum(got.values())


# ------------------------------------------------------------- load ramp


def run_load_ramp() -> None:
    """``bench.py --load-ramp``: prove the elastic autoscaler closes the
    loop with no operator in it. An impulse source paces a scheduled load
    — BASE events/s for 10 s, then a sustained 4x spike — through a keyed
    windowed aggregate whose per-row cost is a GIL-releasing sleep UDF
    (an external-enrichment stand-in: per-subtask capacity is fixed, so
    added parallelism genuinely adds throughput even on a throttled CPU).
    At the base rate one subtask holds the sink p99 under budget; the
    spike melts it; the autoscaler must detect the pressure, rescale
    through the coordinated drain/restore path, burst through the
    backlog, and bring the *windowed* sink p99 back under budget — all
    with zero rescale API calls. Event timestamps are the scheduled
    emission wall time (impulse rate_phases), so sink latency reads
    directly as "seconds behind schedule"."""
    import time as _time

    import arroyo_tpu
    from arroyo_tpu import config as cfg
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import EmbeddedScheduler
    from arroyo_tpu.metrics import SINK_LATENCY_BUCKETS, Histogram, registry
    from arroyo_tpu.udf import register_udf

    arroyo_tpu._load_operators()

    BASE = 6_000          # events/s before the spike
    SPIKE = 4 * BASE      # the 4x traffic spike, sustained
    BASE_SECONDS = 10
    # sleep-modelled per-row enrichment cost: one subtask caps out near
    # 1/60us ~ 16k rows/s, well under the spike and well over the base —
    # the spike NEEDS the rescale, the base must not
    PER_ROW_COST_S = 60e-6
    P99_BUDGET_S = 5.0
    WINDOW_S = 5.0        # sliding window for the p99 readout
    DEADLINE_S = 150.0

    def enrich(x):
        _time.sleep(len(np.asarray(x)) * PER_ROW_COST_S)
        return np.asarray(x, dtype=np.int64)

    register_udf("enrich", enrich, return_dtype="int64", vectorized=True)

    cfg.update({
        "checkpoint.storage-url": "/tmp/arroyo-tpu-bench/ramp-checkpoints",
        "checkpoint.interval-ms": 2000,
        # bigger source batches cut the per-batch Python overhead that
        # would otherwise dominate the sleep-modelled per-row cost
        "pipeline.source-batch-size": 1024,
        "autoscaler.enabled": True,
        "autoscaler.min-parallelism": 1,
        "autoscaler.max-parallelism": 4,
        "autoscaler.up-ticks": 10,
        "autoscaler.up-factor": 4.0,  # one decisive jump for a 4x spike
        "autoscaler.cooldown-s": 5.0,
        "autoscaler.down-ticks": 100_000,  # this run only proves scale-up
        # detection deliberately keys off the SLOW end-latency symptoms
        # (watermark lag / sink p99) with the early-warning queue signals
        # off: the melt must be visible in the p99 readout before the
        # loop reacts, or "returns under budget" proves nothing. A
        # production config would leave backpressure on and act sooner.
        "autoscaler.up-backpressure": 1e12,
        "autoscaler.up-queue-transit-p99-ms": 1e12,
        "autoscaler.up-watermark-lag-s": 4.0,
        "autoscaler.up-sink-latency-p99-s": 6.0,
    })
    import shutil

    shutil.rmtree("/tmp/arroyo-tpu-bench/ramp-checkpoints", ignore_errors=True)

    sql = f"""
CREATE TABLE load (
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'impulse',
  rate_phases = '{BASE}x{BASE * BASE_SECONDS},{SPIKE}'
);
CREATE TABLE ramp_out (
  start TIMESTAMP, g BIGINT, rows BIGINT, mx BIGINT
) WITH (connector = 'blackhole', type = 'sink');
INSERT INTO ramp_out
SELECT window.start AS start, g, rows, mx FROM (
  SELECT tumble(interval '1 second') AS window,
    CAST(counter % 64 AS BIGINT) AS g,
    count(*) AS rows,
    max(enrich(counter)) AS mx
  FROM load
  GROUP BY window, g
) x;
"""

    def sink_hist(jid):
        h = Histogram(SINK_LATENCY_BUCKETS)
        for t in registry.snapshot():
            if t.job_id == jid and t.sink_event_latency.count:
                h.counts = [a + b for a, b in
                            zip(h.counts, t.sink_event_latency.counts)]
                h.count += t.sink_event_latency.count
                h.sum += t.sink_event_latency.sum
        return h

    def windowed_p99(samples):
        """p99 over roughly the last WINDOW_S of sink arrivals: bucket
        difference between the newest cumulative histogram and the one
        ~WINDOW_S ago (counters are monotone across restores — the
        registry outlives embedded worker sets)."""
        if len(samples) < 2:
            return None
        newest_t, newest = samples[-1]
        base_t, base = samples[0]
        for t, h in samples:
            if newest_t - t >= WINDOW_S:
                base_t, base = t, h
        delta = Histogram(SINK_LATENCY_BUCKETS)
        delta.counts = [a - b for a, b in zip(newest.counts, base.counts)]
        delta.count = newest.count - base.count
        delta.sum = newest.sum - base.sum
        if delta.count < 3:  # sink latency observes once per arriving
            return None      # batch (~1/s per closing window round)
        return delta.quantile(0.99)

    db = Database()
    ctl = ControllerServer(db, EmbeddedScheduler()).start()
    timeline: list[dict] = []
    outcome = {"melted": False, "recovered": False, "recovery_s": None,
               "peak_p99_s": None}
    try:
        pid = db.create_pipeline("load-ramp", sql, 1)
        jid = db.create_job(pid)
        ctl.wait_for_state(jid, "Running", timeout=60)
        t0 = _time.monotonic()
        spike_at = t0 + BASE_SECONDS
        samples: list[tuple[float, Histogram]] = []
        recovered_since = None
        while _time.monotonic() - t0 < DEADLINE_S:
            _time.sleep(0.5)
            now = _time.monotonic()
            samples.append((now, sink_hist(jid)))
            samples = [s for s in samples if now - s[0] <= WINDOW_S + 2.0]
            p99 = windowed_p99(samples)
            jc = ctl.jobs.get(jid)
            par = jc.parallelism if jc is not None else None
            state = db.get_job(jid)["state"]
            timeline.append({
                "t_s": round(now - t0, 1), "p99_s": p99 and round(p99, 3),
                "parallelism": par, "state": state,
            })
            if state in ("Failed", "Finished", "Stopped"):
                break
            if now < spike_at or p99 is None:
                continue
            outcome["peak_p99_s"] = max(outcome["peak_p99_s"] or 0.0, p99)
            if p99 > P99_BUDGET_S:
                outcome["melted"] = True
                recovered_since = None
            elif outcome["melted"]:
                # under budget post-melt; require it to HOLD for a window
                recovered_since = recovered_since or now
                if now - recovered_since >= WINDOW_S:
                    outcome["recovered"] = True
                    outcome["recovery_s"] = round(now - spike_at, 1)
                    break
        evs = db.list_events(jid)
        # graceful stop: a final checkpoint drains the workers so engine
        # threads exit cleanly instead of being killed mid-batch
        db.update_job(jid, desired_stop="checkpoint")
        try:
            ctl.wait_for_state(jid, "Stopped", "Failed", "Finished",
                               timeout=45)
        except Exception:  # lint: waive LR102 — bench teardown only
            pass
    finally:
        ctl.stop()

    autoscale = [e["code"] for e in evs if e["code"].startswith("AUTOSCALE")]
    final_par = next((s["parallelism"] for s in reversed(timeline)
                      if s["parallelism"]), None)
    ok = (outcome["melted"] and outcome["recovered"]
          and "AUTOSCALE_DONE" in autoscale)
    print(json.dumps({
        "metric": "load_ramp_autoscale_recovery_seconds",
        "value": outcome["recovery_s"] if ok else None,
        "unit": "s",
        "vs_baseline": None,
        "extra": {
            "ok": ok,
            "base_rate": BASE, "spike_rate": SPIKE,
            "p99_budget_s": P99_BUDGET_S,
            "peak_p99_s": outcome["peak_p99_s"] and round(outcome["peak_p99_s"], 2),
            "melted": outcome["melted"], "recovered": outcome["recovered"],
            "final_parallelism": final_par,
            "autoscale_events": autoscale,
            "manual_rescale_calls": 0,
            "timeline": timeline,
        },
    }))
    sys.exit(0 if ok else 1)


def run_segment_ab() -> None:
    """--segment-compile-ab: whole-segment compilation A/B (ISSUE 12).

    Runs q5/q7/q8 twice each — segment.compile.enabled on vs off, chaining
    on both times, everything else identical — and emits BENCH_r06.json:
    best-of-reps events/s per mode, the compiled/interpreted ratio, and the
    per-operator cost profile embedded for BOTH modes so the chain's
    per-batch dispatch overhead (its 'process' self-time and us/row) is
    visible before/after. The compiled chain profiles as ONE dispatch site;
    its interpreted twin pays N member hook calls per micro-batch.

    Warm-box caveat (BENCH_r05 note): this container's CPU throttling
    swings absolute ev/s >2x between back-to-back runs — judge the A/B
    ratio only on a warm, unthrottled run, and prefer the embedded
    self-time deltas (CPU-clock based) over wall ev/s when they disagree.
    """
    import arroyo_tpu
    from arroyo_tpu import config as cfg
    from arroyo_tpu.metrics import registry
    from arroyo_tpu.obs.profile import job_profile

    arroyo_tpu._load_operators()
    cfg.update({
        "pipeline.chaining.enabled": True,
        "device.table-capacity": 65536,
        "device.emit-capacity": 8192,
        "checkpoint.storage-url": "/tmp/arroyo-tpu-bench/checkpoints",
    })
    events = int(os.environ.get("ARROYO_BENCH_EVENTS", 2_000_000))
    reps = int(os.environ.get("ARROYO_BENCH_REPS", 5))
    DEV_BS = 65536
    configs = [
        ("q7", build_q7, check_parity_q7, events),
        ("q5", build_q5, check_parity_q5, events // 2),
        ("q8", build_q8, check_parity_q8, events // 4),
    ]
    queue_mult = {"q8": 1}
    out: dict = {"events": events, "reps": reps}
    all_ok = True
    for name, build, parity, n_ev in configs:
        per_mode: dict = {"interpreted": {}, "compiled": {}}
        # run_config clears the job's registry per run, so segment stats
        # accumulate HERE across warmup + every compiled rep — the
        # artifact must show where compilation actually happened (the
        # warmup), not just the final warm-cache rep's zeros
        seg_totals = [0, 0]  # compiles, cache hits

        def take_seg_stats():
            c, h = registry.segment_compile_stats(f"bench-{name}-jax")
            seg_totals[0] += c
            seg_totals[1] += h

        def one(enabled: bool) -> float:
            cfg.update({"segment.compile.enabled": enabled})
            gc.collect()
            wall, rows, _lat, _walls = run_config(
                name, build, "jax", n_ev, DEV_BS, queue_mult.get(name, 2))
            parity(rows, n_ev)
            if enabled:
                take_seg_stats()
            return n_ev / wall

        # warmup both modes: the big device shapes AND the segment-cache
        # entries — including the measured run's REMAINDER batch shape
        # (n_ev % batch), so no rep pays a mid-measurement XLA compile
        for enabled in (False, True):
            cfg.update({"segment.compile.enabled": enabled})
            run_config(name, build, "jax",
                       3 * DEV_BS + (n_ev % DEV_BS or DEV_BS), DEV_BS,
                       queue_mult.get(name, 2))
            if enabled:
                take_seg_stats()
        # PAIRED reps, interpreted/compiled back to back on the same box
        # state: container CPU throttling drifts absolute ev/s >2x across
        # seconds, so unpaired mode blocks measure the throttle, not the
        # change; the per-pair ratio cancels the drift (the PR 5 bench's
        # back-to-back A/B protocol), judged on the median pair
        ratios: list[float] = []
        for r in range(reps):
            eps_i = one(False)
            prof_i = job_profile(registry.job_metrics(f"bench-{name}-jax"))
            eps_c = one(True)
            prof_c = job_profile(registry.job_metrics(f"bench-{name}-jax"))
            ratios.append(eps_c / eps_i)
            print(f"# {name} pair {r}: interpreted {eps_i:,.0f} ev/s, "
                  f"compiled {eps_c:,.0f} ev/s, ratio {eps_c / eps_i:.3f}",
                  file=sys.stderr)
            if eps_i > per_mode["interpreted"].get("events_per_sec", 0):
                per_mode["interpreted"] = {
                    "events_per_sec": round(eps_i, 1), "profile": prof_i}
            if eps_c > per_mode["compiled"].get("events_per_sec", 0):
                per_mode["compiled"] = {
                    "events_per_sec": round(eps_c, 1), "profile": prof_c}
        per_mode["compiled"]["segment_compiles"] = seg_totals[0]
        per_mode["compiled"]["segment_cache_hits"] = seg_totals[1]
        # judged like every ev/s number in this series: on the least-
        # throttled (best) pair — the repo's best-of-N convention for this
        # container's one-sided CPU-throttling noise — with the median as
        # a no-hidden-regression guard (a real slowdown drags BOTH)
        best_pair = max(ratios)
        median = statistics.median(ratios)
        ok = best_pair >= 1.0 and median >= 0.97
        all_ok = all_ok and ok
        print(f"# {name}: compiled/interpreted best pair {best_pair:.3f}, "
              f"median of {len(ratios)} pairs {median:.3f} "
              f"({'OK' if ok else 'REGRESSION'})", file=sys.stderr)
        out[name] = {**per_mode,
                     "pair_ratios": [round(x, 3) for x in ratios],
                     "compiled_over_interpreted": round(best_pair, 3),
                     "pair_ratio_median": round(median, 3),
                     "dispatch_overhead_eliminated": ok}
    payload = {
        "metric": "segment_compile_ab_min_ratio",
        "value": round(min(out[c[0]]["compiled_over_interpreted"]
                           for c in configs), 3),
        "unit": "compiled/interpreted events-per-sec ratio, best of paired "
                "back-to-back reps (>=1 = dispatch overhead eliminated; "
                "pair_ratio_median >= 0.97 guards against a hidden "
                "regression)",
        "platform": os.environ.get("JAX_PLATFORMS", "default"),
        "note": "warm-box caveat: container CPU throttling swings absolute "
                "ev/s >2x run-to-run, so reps pair interpreted/compiled "
                "back to back, the ratio is judged on the least-throttled "
                "pair (the series' best-of-N convention), and the median "
                "is reported alongside; judge absolute ev/s on a warm run "
                "only",
        "extra": out,
    }
    with open("BENCH_r06.json", "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(json.dumps(payload))
    sys.exit(0 if all_ok else 1)


def run_mesh_ab() -> None:
    """--mesh-ab: fused shard_map segment vs host-shuffle mesh A/B
    (ISSUE 20), emitting MULTICHIP_r06.json.

    One pipeline — impulse -> watermark -> key -> tumbling count/sum over
    an 8-way key-sharded aggregate -> vec sink — run two ways, paired back
    to back per rep:

      fused:  the compiled segment runs INSIDE the sharded aggregate's one
              shard_map'd jitted program per micro-batch
              (segment.compile.mesh-fuse on);
      host:   the same compiled segment on host, feeding the aggregate's
              per-batch host bucketing + device all_to_all exchange
              (mesh-fuse off) — the pre-fusion mesh path.

    Both modes' outputs are verified exactly against a closed-form oracle,
    and the artifact embeds the dispatch ledger per mode: segment-level
    fused dispatches MUST equal aggregate-level program executions
    (calls_per_step == 1.0), so 'one jitted call per step' is data in the
    artifact, not prose. Runs on 8 EMULATED host devices
    (--xla_force_host_platform_device_count; the container's tunnel
    exposes one real chip), so absolute ev/s is a CPU number — judge the
    ledger and the paired ratio, not the wall clock. When fewer than 8
    devices materialize the artifact records skipped=true and exits 0
    (r01-r05 convention)."""
    import tempfile

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")  # tunnel shim override
    except Exception:
        pass

    import arroyo_tpu
    from arroyo_tpu import config as cfg
    from arroyo_tpu.engine import Engine
    from arroyo_tpu.engine.segment import (mesh_dispatch_counts,
                                           reset_mesh_dispatch_counts)
    from arroyo_tpu.parallel import can_make
    from arroyo_tpu.parallel.sharded_agg import (dispatch_counts,
                                                 reset_dispatch_counts)

    n_dev = 8
    if not can_make(n_dev):
        payload = {"n_devices": len(jax.devices()), "rc": 0, "ok": False,
                   "skipped": True,
                   "tail": f"mesh-ab skipped: {len(jax.devices())} devices "
                           f"< {n_dev} (set XLA_FLAGS="
                           f"--xla_force_host_platform_device_count=8)"}
        with open("MULTICHIP_r06.json", "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
        print(json.dumps(payload))
        sys.exit(0)

    arroyo_tpu._load_operators()
    count, width, nkeys = int(os.environ.get("ARROYO_BENCH_EVENTS", 200_000)), 1_000_000, 7
    reps = int(os.environ.get("ARROYO_BENCH_REPS", 3))
    BS = 4096
    cfg.update({
        "checkpoint.storage-url": tempfile.mkdtemp(prefix="arroyo-mesh-ab-"),
        "device.mesh-devices": n_dev,
        "device.table-capacity": 8192, "device.batch-capacity": 2048,
        "device.emit-capacity": 4096, "device.spill-capacity": 4096,
        "device.max-probes": 32,
        "pipeline.chaining.enabled": True,
        "pipeline.source-batch-size": BS,
        "engine.coalesce.max-rows": BS,
        "segment.compile.min-rows": 1,
    })

    from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
    from arroyo_tpu.expr import BinOp, Col, Lit
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])

    def mk(rows):
        g = Graph()
        g.add_node(Node("src", OpName.SOURCE, {
            "connector": "impulse", "message_count": count,
            "interval_micros": 1000, "start_time_micros": 0,
            "event_rate": 0}, 1))
        g.add_node(Node("wm", OpName.WATERMARK, {"expr": Col(TIMESTAMP_FIELD)}, 1))
        g.add_node(Node("key", OpName.KEY, {
            "keys": [("k", BinOp("%", Col("counter"), Lit(nkeys)))]}, 1))
        g.add_node(Node("agg", OpName.TUMBLING_AGGREGATE, {
            "width_micros": width, "key_fields": ["k"],
            "aggregates": [("cnt", "count", None),
                           ("total", "sum", Col("counter"))],
            "input_dtype_of": lambda e: np.dtype(np.int64),
            "backend": "jax"}, 1))
        g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows}, 1))
        g.add_edge("src", "wm", EdgeType.FORWARD, S)
        g.add_edge("wm", "key", EdgeType.FORWARD, S)
        g.add_edge("key", "agg", EdgeType.SHUFFLE, S)
        g.add_edge("agg", "sink", EdgeType.FORWARD, S)
        return g

    want: dict = {}
    for c in range(count):
        w, k = (c * 1000) // width, c % nkeys
        cnt, tot = want.get((w, k), (0, 0))
        want[(w, k)] = (cnt + 1, tot + c)

    def one(fuse: bool, tag: str):
        cfg.update({"segment.compile.mesh-fuse": fuse})
        reset_mesh_dispatch_counts()
        reset_dispatch_counts()
        rows: list = []
        gc.collect()
        eng = Engine(mk(rows), job_id=f"mesh-ab-{tag}")
        t0 = time.perf_counter()
        eng.run_to_completion(timeout=600)
        wall = time.perf_counter() - t0
        got = {(r["window_start"] // width, r["k"]): (r["cnt"], r["total"])
               for r in rows}
        assert got == want, f"mesh-ab {tag}: output diverged from oracle"
        return count / wall, mesh_dispatch_counts(), dispatch_counts()

    # warmup both modes: XLA program compiles + segment cache entries
    # (including the remainder-batch shape) happen here, not mid-rep
    one(False, "warm-host")
    one(True, "warm-fused")

    modes: dict = {"fused": {}, "host": {}}
    ratios: list[float] = []
    ledger_ok = True
    for r in range(reps):
        eps_h, _seg_h, agg_h = one(False, f"host-{r}")
        eps_f, seg_f, agg_f = one(True, f"fused-{r}")
        ratios.append(eps_f / eps_h)
        # the tentpole's proof obligation: every fused segment dispatch is
        # exactly one program execution, and the fused path actually ran
        cps = (agg_f["fused_steps"] / seg_f["fused"]) if seg_f["fused"] else 0.0
        ledger_ok = ledger_ok and seg_f["fused"] > 0 and cps == 1.0 \
            and agg_h["fused_steps"] == 0 and agg_h["host_steps"] > 0
        print(f"# mesh-ab pair {r}: host {eps_h:,.0f} ev/s, fused "
              f"{eps_f:,.0f} ev/s, ratio {eps_f / eps_h:.3f}, fused "
              f"dispatches {seg_f['fused']} (calls/step {cps:.1f})",
              file=sys.stderr)
        if eps_h > modes["host"].get("events_per_sec", 0):
            modes["host"] = {"events_per_sec": round(eps_h, 1),
                             "dispatch": {"segment_fused": 0,
                                          "agg_program_steps": agg_h["fused_steps"],
                                          "agg_host_exchange_steps": agg_h["host_steps"]}}
        if eps_f > modes["fused"].get("events_per_sec", 0):
            modes["fused"] = {"events_per_sec": round(eps_f, 1),
                              "dispatch": {"segment_fused": seg_f["fused"],
                                           "segment_host_commits": seg_f["host"],
                                           "agg_program_steps": agg_f["fused_steps"],
                                           "agg_host_exchange_steps": agg_f["host_steps"],
                                           "calls_per_step": round(cps, 3)}}
    best, median = max(ratios), statistics.median(ratios)
    ok = ledger_ok and best >= 1.0
    tail = (f"mesh-ab OK: 8 devices, fused/host best {best:.3f} (median "
            f"{median:.3f}), {modes['fused']['dispatch']['segment_fused']} "
            f"fused steps at calls/step "
            f"{modes['fused']['dispatch']['calls_per_step']:.1f}, oracle "
            f"exact both modes" if ok else
            f"mesh-ab REGRESSION: ratio best {best:.3f} median {median:.3f} "
            f"ledger_ok={ledger_ok}")
    payload = {
        "n_devices": n_dev, "rc": 0 if ok else 1, "ok": ok, "skipped": False,
        "tail": tail,
        "metric": "mesh_fused_over_host_events_per_sec",
        "value": round(best, 3),
        "unit": "fused/host events-per-sec ratio, best of paired reps on 8 "
                "emulated CPU devices (ledger proves one jitted program "
                "execution per fused micro-batch)",
        "extra": {"events": count, "reps": reps,
                  "pair_ratios": [round(x, 3) for x in ratios],
                  "pair_ratio_median": round(median, 3),
                  "one_call_per_step": ledger_ok, **modes},
    }
    with open("MULTICHIP_r06.json", "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(json.dumps(payload))
    sys.exit(0 if ok else 1)


def _probe_default_platform(attempts: int = 4, retry_delay_s: float = 30.0) -> str:
    """Platform kind ("tpu"/"cpu"/...) when the default jax platform (the
    TPU tunnel under the driver) initializes AND can run a computation, or
    "" when it cannot. Probed in a subprocess because a wedged tunnel HANGS
    backend init rather than raising. Retries with a delay: the tunnel can
    come up seconds after the container does (r04 lost its TPU number to a
    single-shot probe)."""
    import subprocess

    code = ("import jax, jax.numpy as jnp; d = jax.devices();"
            "x = jnp.arange(8); (x + 1).block_until_ready();"
            "print(d[0].platform)")
    for i in range(attempts):
        if i:
            print(f"# platform probe attempt {i} failed; retrying in "
                  f"{retry_delay_s:.0f}s", file=sys.stderr)
            time.sleep(retry_delay_s)
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, timeout=240, text=True,
            )
            if r.returncode == 0:
                return r.stdout.strip().splitlines()[-1]
        except subprocess.TimeoutExpired:
            pass
    return ""


def main() -> None:
    # --profile: embed the per-operator cost profile (self-time, busy%,
    # state sizes, hot keys — obs/profile.py, same data `explain` renders)
    # under extra.<cfg>.profile so future perf PRs can attribute wins per
    # operator straight from the BENCH_*.json archive. Taken from the LAST
    # rep (run_config clears the registry per rep).
    # --load-ramp: the autoscaler acceptance run (CPU-bound control-loop
    # proof, not a device benchmark) — see run_load_ramp
    if "--load-ramp" in sys.argv[1:]:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        run_load_ramp()
        return
    if "--mesh-ab" in sys.argv[1:]:
        # fused shard_map segment A/B on 8 emulated host devices: force
        # the flags BEFORE any backend init (jax reads XLA_FLAGS once)
        os.environ["JAX_PLATFORMS"] = "cpu"
        _fl = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in _fl:
            os.environ["XLA_FLAGS"] = (
                _fl + " --xla_force_host_platform_device_count=8").strip()
        run_mesh_ab()
        return
    if "--segment-compile-ab" in sys.argv[1:]:
        # whole-segment compilation A/B: the win being measured is the
        # collapse of host-side Python dispatch, so CPU is the honest
        # default platform (a TPU run would conflate device lowering)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        run_segment_ab()
        return
    embed_profile = "--profile" in sys.argv[1:]
    platform = None
    if os.environ.get("ARROYO_BENCH_PLATFORM"):
        platform = os.environ["ARROYO_BENCH_PLATFORM"]
        import jax

        jax.config.update("jax_platforms", platform)
    else:
        platform = _probe_default_platform()
        if not platform:
            # the accelerator link is down: a degraded CPU measurement with
            # an explicit marker beats ending the round with no number at
            # all — but it must NEVER masquerade as the chip number (the
            # metric name changes and vs_baseline is null below)
            platform = "cpu-fallback"
            print("# WARNING: default platform failed to initialize after "
                  "retries; benchmarking on CPU fallback", file=sys.stderr)
            import jax

            jax.config.update("jax_platforms", "cpu")
        else:
            print(f"# default platform OK: {platform}", file=sys.stderr)
    import arroyo_tpu
    from arroyo_tpu import config as cfg

    arroyo_tpu._load_operators()
    cfg.update({
        "pipeline.chaining.enabled": True,
        "device.table-capacity": 65536,
        "device.emit-capacity": 8192,
        "checkpoint.storage-url": "/tmp/arroyo-tpu-bench/checkpoints",
    })

    events = int(os.environ.get("ARROYO_BENCH_EVENTS", 2_000_000))
    # same event count as the measured runs: best-of-N on one size vs
    # best-of-N on another was apples-to-pears
    base_events = int(os.environ.get("ARROYO_BENCH_BASELINE_EVENTS", events))
    reps = int(os.environ.get("ARROYO_BENCH_REPS", 3))
    # 65536 is the device-link sweet spot after the count-lane/int32-slot
    # byte cuts; the numpy dict-store baseline prefers smaller batches
    DEV_BS, NP_BS = 65536, 8192

    def window_end_tumbling(batch):
        return np.asarray(batch["window_start"]) + WIDTH

    def window_end_q8(batch):
        from arroyo_tpu.batch import TIMESTAMP_FIELD

        return np.asarray(batch[TIMESTAMP_FIELD]) + WIDTH

    def window_end_session(batch):
        return np.asarray(batch["window_end"])

    configs = [
        ("q7", build_q7, check_parity_q7, window_end_tumbling, events),
        ("q5", build_q5, check_parity_q5, window_end_tumbling, events // 2),
        ("q8", build_q8, check_parity_q8, window_end_q8, events // 4),
        ("qs", build_qs, check_parity_qs, window_end_session, events // 4),
    ]
    QUEUE_MULT_DEFAULT = 2
    queue_mult = {"q8": 1}
    # p99 watermark-to-emit budgets (VERDICT r4 #4); recorded as explicit
    # pass/fail flags rather than assertions so a miss can never zero the
    # round's number the way r03's crash did
    P99_BUDGET_MS = {"q8": 50.0, "qs": 100.0}
    extra: dict = {}
    q7_eps = 0.0
    for name, build, parity, wend, n_ev in configs:
        # warmup must see at least one FULL-size batch: a 50k-event warmup
        # never produces a 65536-row batch, so the real run's first batch
        # would trigger the big-shape compile mid-measurement (slow rep 0,
        # ~20-40s per shape on TPU)
        run_config(name, build, "jax", 3 * DEV_BS, DEV_BS,
                   queue_mult.get(name, QUEUE_MULT_DEFAULT))
        best_eps, best_lat = 0.0, (None, None)
        worst_p99 = None
        for r in range(reps):
            gc.collect()
            wall, rows, lat_log, walls = run_config(
                name, build, "jax", n_ev, DEV_BS, queue_mult.get(name, QUEUE_MULT_DEFAULT))
            parity(rows, n_ev)
            eps = n_ev / wall
            p50, p99, n_l = latency_percentiles(rows, lat_log, walls, wend)
            print(f"# {name} rep {r}: {n_ev} events in {wall:.2f}s = {eps:,.0f} ev/s; "
                  f"parity OK; p50 {p50 and round(p50, 1)}ms p99 {p99 and round(p99, 1)}ms "
                  f"({n_l} rows)", file=sys.stderr)
            if eps > best_eps:
                best_eps, best_lat = eps, (p50, p99)
            if p99 is not None and (worst_p99 is None or p99 > worst_p99):
                worst_p99 = p99
        em, qt, sk = coalesce_breakdown(f"bench-{name}-jax")
        print(f"# {name} coalesce: {em.count} emitted batches, "
              f"mean {em.mean():,.0f} rows/batch; queue transit "
              f"p50 {qt.quantile_str(0.5, scale=1000)}ms "
              f"p99 {qt.quantile_str(0.99, scale=1000)}ms ({qt.count} transits)",
              file=sys.stderr)
        extra[name] = {
            "events_per_sec": round(best_eps, 1),
            "p50_ms": best_lat[0] and round(best_lat[0], 2),
            "p99_ms": best_lat[1] and round(best_lat[1], 2),
            "coalesce": {
                "emitted_batches": em.count,
                "mean_emit_rows": round(em.mean(), 1),
                "queue_transit_p99_ms": round(qt.quantile(0.99) * 1000, 3),
            },
            # full distribution summaries so the perf trajectory captures
            # latency shapes, not just ev/s (BENCH_*.json archives these)
            "metrics": {
                "emit_batch_rows": histogram_summary(em),
                "queue_transit_ms": histogram_summary(qt, scale=1000),
                "sink_event_latency_s": histogram_summary(sk),
            },
        }
        if embed_profile:
            from arroyo_tpu.metrics import registry as _registry
            from arroyo_tpu.obs.profile import job_profile

            extra[name]["profile"] = job_profile(
                _registry.job_metrics(f"bench-{name}-jax"))
        budget = P99_BUDGET_MS.get(name)
        if budget is not None:
            # judged on the WORST rep: one blown rep is a blown budget; an
            # explicit null marks "p99 not measurable", distinct from pass
            extra[name]["p99_budget_ms"] = budget
            extra[name]["p99_worst_ms"] = worst_p99 and round(worst_p99, 2)
            extra[name]["p99_budget_ok"] = (
                None if worst_p99 is None else bool(worst_p99 <= budget))
        if name == "q7":
            q7_eps = best_eps

    # CPU baseline proxy: q7 on the numpy dict-store backend
    b_eps = 0.0
    for r in range(reps):
        gc.collect()
        wall, rows, _lat, _walls = run_config("q7", build_q7, "numpy", base_events, NP_BS)
        check_parity_q7(rows, base_events)
        print(f"# q7 numpy-baseline rep {r}: {base_events} events in {wall:.2f}s = "
              f"{base_events / wall:,.0f} ev/s", file=sys.stderr)
        b_eps = max(b_eps, base_events / wall)
    extra["q7_numpy_baseline_events_per_sec"] = round(b_eps, 1)

    fallback = platform == "cpu-fallback"
    extra["platform"] = ("cpu-fallback (accelerator link unavailable)"
                         if fallback else platform)
    # always carried: on a fallback run this is the ONLY comparison ratio
    # (vs_baseline is nulled below so it can't pose as the chip number)
    extra["vs_local_numpy"] = round(q7_eps / b_eps, 3)
    print(json.dumps({
        # a CPU-fallback run gets a DISTINCT metric name and a null
        # vs_baseline so it can never be read as the per-chip number
        "metric": ("nexmark_q7_tumbling_max_events_per_sec_CPU_FALLBACK"
                   if fallback else
                   "nexmark_q7_tumbling_max_events_per_sec_per_chip"),
        "value": round(q7_eps, 1),
        "unit": "events/s",
        "vs_baseline": None if fallback else round(q7_eps / b_eps, 3),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
