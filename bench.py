#!/usr/bin/env python
"""Round benchmark: Nexmark q7-style windowed aggregate throughput.

Pipeline (the BASELINE.md north-star shape): nexmark bid stream ->
filter/project -> expression watermark -> key by auction -> 10s tumbling
MAX(price)+COUNT -> blackhole sink. Runs the full framework (vectorized
generator, host engine, device aggregation steps) on the default platform
(the real TPU chip under the driver), then the identical pipeline on the
pure-NumPy aggregation backend as the CPU baseline proxy.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def build_graph(rows_sink, backend: str, event_count: int):
    from arroyo_tpu.batch import TIMESTAMP_FIELD, Schema
    from arroyo_tpu.expr import Col
    from arroyo_tpu.graph import EdgeType, Graph, Node, OpName

    S = Schema.of([("x", "int64"), (TIMESTAMP_FIELD, "int64")])
    g = Graph()
    g.add_node(Node("src", OpName.SOURCE, {
        "connector": "nexmark", "event_count": event_count,
        "inter_event_micros": 1000, "first_event_micros": 0,
        "include_strings": False,
        # projection pushdown: q7 reads only the bid auction/price lanes
        # (the reference planner pushes projections into scans the same way)
        "columns": ["bid.auction", "bid.price"]}, 1))
    g.add_node(Node("bids", OpName.VALUE, {
        "projections": [("auction", Col("bid.auction")), ("price", Col("bid.price"))],
        "filter": Col("bid")}, 1))
    # periodic watermarks (1s event time): window closes batch up instead of
    # firing a device extraction per micro-batch (the reference emits
    # watermarks on an interval too; dense per-batch watermarks are a
    # correctness-test setting, not a throughput one)
    g.add_node(Node("wm", OpName.WATERMARK, {
        "expr": Col(TIMESTAMP_FIELD), "interval_micros": 1_000_000}, 1))
    g.add_node(Node("key", OpName.KEY, {"keys": [("auction", Col("auction"))]}, 1))
    g.add_node(Node("agg", OpName.TUMBLING_AGGREGATE, {
        "width_micros": 10_000_000,
        "key_fields": ["auction"],
        "aggregates": [("max_price", "max", Col("price")), ("bids", "count", None)],
        "input_dtype_of": lambda e: np.dtype(np.int64),
        "backend": backend}, 1))
    g.add_node(Node("sink", OpName.SINK, {"connector": "vec", "rows": rows_sink, "columnar": True}, 1))
    g.add_edge("src", "bids", EdgeType.FORWARD, S)
    g.add_edge("bids", "wm", EdgeType.FORWARD, S)
    g.add_edge("wm", "key", EdgeType.FORWARD, S)
    g.add_edge("key", "agg", EdgeType.SHUFFLE, S)
    g.add_edge("agg", "sink", EdgeType.FORWARD, S)
    return g


def run_once(backend: str, event_count: int, batch_size: int = None) -> tuple[float, int, list]:
    from arroyo_tpu import config as cfg
    from arroyo_tpu.engine import run_graph

    if batch_size is not None:
        # each backend runs at its own best batch size and queue depth (the
        # device path amortizes dispatch/fetch round trips over bigger
        # batches and overlaps source generation behind a deep queue; the
        # numpy baseline's dict store prefers small batches and lockstep)
        cfg.update({
            "pipeline.source-batch-size": batch_size,
            "device.batch-capacity": batch_size,
            "worker.queue-size": 4 * batch_size if backend == "jax" else batch_size,
        })
    rows: list = []
    g = build_graph(rows, backend, event_count)
    t0 = time.perf_counter()
    run_graph(g, job_id=f"bench-{backend}", timeout=1800)
    wall = time.perf_counter() - t0
    return wall, event_count, rows


def main() -> None:
    if os.environ.get("ARROYO_BENCH_PLATFORM"):
        import jax

        jax.config.update("jax_platforms", os.environ["ARROYO_BENCH_PLATFORM"])
    import arroyo_tpu
    from arroyo_tpu import config as cfg

    arroyo_tpu._load_operators()
    cfg.update({
        "pipeline.source-batch-size": 8192,
        "pipeline.chaining.enabled": True,
        "device.batch-capacity": 8192,
        "device.table-capacity": 65536,
        "device.emit-capacity": 8192,
        "checkpoint.storage-url": "/tmp/arroyo-tpu-bench/checkpoints",
    })

    events = int(os.environ.get("ARROYO_BENCH_EVENTS", 2_000_000))
    base_events = int(os.environ.get("ARROYO_BENCH_BASELINE_EVENTS", 500_000))

    # warm-up: compile the device step on small input
    w_wall, _, _ = run_once("jax", 50_000, batch_size=65536)
    print(f"# warmup (compile): {w_wall:.1f}s", file=sys.stderr)

    # the remote-device tunnel has +-25% run-to-run variance; report the
    # best of 3 (parity asserted on every run)
    import gc

    reps = int(os.environ.get("ARROYO_BENCH_REPS", 3))
    eps = 0.0
    for r in range(reps):
        gc.collect()
        # 65536 is the tunnel sweet spot after the count-lane/int32-slot byte
        # cuts (measured sweep: 65536 best ~1.7M ev/s vs 32768 ~1.26M)
        wall, n, rows = run_once("jax", events, batch_size=65536)
        expected_bids = int(n * 46 / 50)
        got_bids = sum(int(b["bids"].sum()) for b in rows)
        assert got_bids == expected_bids, f"parity failure: {got_bids} != {expected_bids}"
        print(f"# tpu-path rep {r}: {n} events in {wall:.2f}s = {n/wall:,.0f} events/s; "
              f"{sum(b.num_rows for b in rows)} windows, parity OK", file=sys.stderr)
        eps = max(eps, n / wall)

    b_eps = 0.0
    for r in range(reps):
        gc.collect()
        b_wall, b_n, b_rows = run_once("numpy", base_events, batch_size=8192)
        assert sum(int(b["bids"].sum()) for b in b_rows) == int(b_n * 46 / 50)
        print(f"# numpy-baseline rep {r}: {b_n} events in {b_wall:.2f}s = "
              f"{b_n/b_wall:,.0f} events/s", file=sys.stderr)
        b_eps = max(b_eps, b_n / b_wall)

    print(json.dumps({
        "metric": "nexmark_q7_tumbling_max_events_per_sec_per_chip",
        "value": round(eps, 1),
        "unit": "events/s",
        "vs_baseline": round(eps / b_eps, 3),
    }))


if __name__ == "__main__":
    main()
